"""MatchServer under chaos: P2P matches served from batch slots while the
network misbehaves and the server process itself is killed and restarted.

Three layers:

- :class:`ServerKillRestart` plan plumbing — generation, JSON roundtrip,
  seed-replayability (the serve-tier failure script is one artifact).
- A non-slow smoke: a small server hosting peer-0 of real P2P matches over
  the loopback transport is kill -9'd mid-match and restarted from its
  periodic checkpoint; every match rejoins through the supervisor's
  crash-restart path and converges bitwise with its surviving peer.
- The slow acceptance soak (S=16): loss/reorder/duplicate/corrupt windows,
  an asymmetric partition, one external-peer kill/restart AND one server
  kill/restart — zero desyncs, bounded recovery, no evictions, and one
  match's full confirmed-input log replayed serially from scratch must
  reproduce the recorded checksums bitwise.

KillRestart-family directives are executed at the HARNESS level (a socket
can't kill a process) — the same contract as tests/test_chaos_soak.py.
"""

import os

import numpy as np
import pytest

from bevy_ggrs_tpu.chaos import (
    ChaosPlan,
    ChaosSocket,
    CheckpointCorrupt,
    Corrupt,
    Duplicate,
    KillRestart,
    LossBurst,
    Partition,
    Reorder,
    ServerKillRestart,
    SnapshotCorrupt,
)
from bevy_ggrs_tpu import integrity
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.obs import (
    FlightRecorder,
    ProvenanceLog,
    SidecarSocket,
    SpanTracer,
    SpeculationLedger,
    frame_flows,
    merge_traces,
)
from bevy_ggrs_tpu.relay import RelayServer, RelaySocket, peer_addr
from bevy_ggrs_tpu.runner import RollbackRunner
from bevy_ggrs_tpu.serve import MatchServer, SlotHealth
from bevy_ggrs_tpu.session import (
    PlayerType,
    PredictionThreshold,
    SessionBuilder,
    SessionState,
)
from bevy_ggrs_tpu.session.requests import AdvanceFrame, SaveGameState
from bevy_ggrs_tpu.session.supervisor import Health, SessionSupervisor
from bevy_ggrs_tpu.transport.loopback import LoopbackNetwork
from bevy_ggrs_tpu.utils.metrics import Metrics
from tests.test_p2p import FPS_DT, scripted_input
from tests.test_supervisor import settled_checksums

MAX_PRED = 8
BRANCHES = 8
SPEC_FRAMES = 3


# ---------------------------------------------------------------------------
# ServerKillRestart: plan plumbing
# ---------------------------------------------------------------------------


def test_server_kill_restart_generated_and_replayable():
    peers = (("peer", 0), ("peer", 1))
    plan = ChaosPlan.generate(
        41, 30.0, peers, kill_restart=True, relay=("relay", 0),
        match_server=("srv", 0),
    )
    skrs = plan.server_kill_restarts()
    assert len(skrs) == 1
    (skr,) = skrs
    assert skr.server == ("srv", 0)
    # Late in the run, layered onto the network-fault windows.
    assert 0.55 * 30.0 <= skr.at <= 0.75 * 30.0
    assert skr.down_for > 0
    assert plan.horizon() >= skr.at + skr.down_for
    # Same arguments -> the identical plan, always (seed replay).
    again = ChaosPlan.generate(
        41, 30.0, peers, kill_restart=True, relay=("relay", 0),
        match_server=("srv", 0),
    )
    assert again == plan
    # Leaving the server out never perturbs the rest of the schedule.
    without = ChaosPlan.generate(
        41, 30.0, peers, kill_restart=True, relay=("relay", 0)
    )
    assert without.directives == plan.directives[:-1]


def test_server_kill_restart_json_roundtrip():
    plan = ChaosPlan(
        7,
        (
            LossBurst(1.0, 2.0, 0.2),
            ServerKillRestart(5.0, ("srv", 3), 1.5),
            KillRestart(3.0, ("ext", 0), 1.0),
        ),
    )
    back = ChaosPlan.from_json(plan.to_json())
    assert back == plan  # tuple addresses normalized back from JSON lists
    assert back.server_kill_restarts()[0].server == ("srv", 3)


# ---------------------------------------------------------------------------
# Served-P2P harness
# ---------------------------------------------------------------------------


def server_inputs(frame, handle):
    return scripted_input(handle, frame)


def build_server(ckpt_dir, capacity, groups, net, metrics, tracer=None,
                 ledger=None):
    server = MatchServer(
        box_game.make_schedule(), box_game.make_world(2).commit(),
        MAX_PRED, 2, box_game.INPUT_SPEC,
        capacity=capacity, stagger_groups=groups,
        num_branches=BRANCHES, spec_frames=SPEC_FRAMES,
        metrics=metrics, clock=lambda: net.now, tracer=tracer,
        checkpoint_dir=ckpt_dir, checkpoint_interval=120,
        ledger=ledger,
        # A tight attestation cadence (every 4 frames vs the ring's depth
        # of MAX_PRED+1 rows) so harness-injected SnapshotCorrupt bit
        # flips are caught while the corrupt row is still resident.
        attest_interval=4,
    )
    server.warmup()
    return server


def make_host_session(net, m, tap=None):
    """The server-side session of match ``m``: local player 0 at
    ("srv", m), remote player 1 at ("ext", m). ``tap`` (optional) wraps
    the raw socket in a passive provenance sidecar — all host sessions
    share one "server" log, matching the server tracer's process row."""
    builder = (
        SessionBuilder(box_game.INPUT_SPEC)
        .with_num_players(2)
        .with_max_prediction_window(MAX_PRED)
        .with_disconnect_timeout(1.0)
    )
    builder.add_player(PlayerType.local(), 0)
    builder.add_player(PlayerType.remote(("ext", m)), 1)
    sock = net.socket(("srv", m))
    if tap is not None:
        sock = tap(sock, "server", 500)
    return builder.start_p2p_session(sock, clock=lambda: net.now)


def make_ext_peer(net, m, plan=None, tap=None):
    """The external peer of match ``m``: its own supervised singleton stack
    (session + RollbackRunner + SessionSupervisor), chaos-wrapped. The
    provenance ``tap`` goes on the RAW socket, below the ChaosSocket, so
    it records what actually crossed the wire (drops included)."""
    builder = (
        SessionBuilder(box_game.INPUT_SPEC)
        .with_num_players(2)
        .with_max_prediction_window(MAX_PRED)
        .with_disconnect_timeout(1.0)
    )
    builder.add_player(PlayerType.remote(("srv", m)), 0)
    builder.add_player(PlayerType.local(), 1)
    sock = net.socket(("ext", m))
    if tap is not None:
        sock = tap(sock, f"ext{m}", 600 + m)
    session = builder.start_p2p_session(sock, clock=lambda: net.now)
    if plan is not None:
        session.socket = ChaosSocket(
            session.socket, plan, clock=lambda: net.now, addr=("ext", m)
        )
    runner = RollbackRunner(
        box_game.make_schedule(), box_game.make_world(2).commit(),
        max_prediction=MAX_PRED, num_players=2,
        input_spec=box_game.INPUT_SPEC,
    )
    metrics = Metrics()
    sup = SessionSupervisor(session, runner, metrics=metrics)
    return (session, runner, sup, metrics)


def ext_step(net, peer, canon=None):
    """One external-peer drive iteration (the supervisor drive contract),
    optionally recording the canonical per-frame (bits, status) — rollback
    corrections overwrite predictions, so ``canon`` converges to the
    as-executed confirmed input log."""
    session, runner, sup, _ = peer
    session.poll_remote_clients()
    sup.tick(net.now)
    if session.current_state() != SessionState.RUNNING:
        return
    if not sup.should_advance():
        return
    for _ in range(1 + min(sup.frames_behind(), 4)):
        for h in session.local_player_handles():
            session.add_local_input(
                h, sup.input_for(h, scripted_input(h, session.current_frame))
            )
        try:
            requests = session.advance_frame()
        except PredictionThreshold:
            break
        if canon is not None:
            f = None
            for r in requests:
                if isinstance(r, SaveGameState):
                    f = r.frame
                elif isinstance(r, AdvanceFrame) and f is not None:
                    canon[f] = (
                        np.array(r.bits, copy=True),
                        np.array(r.status, copy=True),
                    )
                    f = None
        runner.handle_requests(requests, session)


def run_served_soak(
    plan, n_matches, n_iters, capacity, groups, ckpt_dir, canon_match=None
):
    """Drive ``n_matches`` served-P2P matches under ``plan``, executing
    peer KillRestart and ServerKillRestart directives at the harness level.
    Returns (server, ext peers, handle map, restore frame, canon log,
    faults, server metrics)."""
    net = LoopbackNetwork()
    metrics = Metrics()
    obs_dir = os.environ.get("GGRS_OBS_DIR")
    # When GGRS_OBS_DIR is set the soak also captures the fleet-trace
    # artifact set — a server SpanTracer plus passive provenance sidecars
    # on every raw socket — without changing the soak's topology (the
    # sidecars transmit nothing; see tests/test_telemetry_determinism.py).
    # Logs live HERE (not in the peers) so kill/restart cycles append to
    # one continuous per-component timeline.
    tracer = (
        SpanTracer(clock=lambda: net.now, pid=500, process_name="server")
        if obs_dir else None
    )
    prov = {}

    def tap(sock, component, pid):
        log = prov.get(component)
        if log is None:
            log = prov[component] = ProvenanceLog(
                component, pid=pid, clock=lambda: net.now
            )
        return SidecarSocket(sock, log)

    tap = tap if obs_dir else None
    # One server-lifetime speculation ledger: passed through kill/restart
    # rebuilds (like the tracer) so blame/economics stay one timeline.
    ledger = (
        SpeculationLedger(component="spec-ledger", pid=501)
        if obs_dir else None
    )
    server = build_server(ckpt_dir, capacity, groups, net, metrics, tracer,
                          ledger)
    ext = {m: make_ext_peer(net, m, plan, tap) for m in range(n_matches)}
    handle_of = {
        m: server.add_match(make_host_session(net, m, tap), server_inputs)
        for m in range(n_matches)
    }
    canon = {} if canon_match is not None else None
    kills = [
        {"at": k.at, "until": k.at + k.down_for, "me": k.peer[1],
         "killed": False, "done": False}
        for k in plan.kill_restarts()
    ]
    skrs = [
        {"at": k.at, "until": k.at + k.down_for,
         "killed": False, "done": False}
        for k in plan.server_kill_restarts()
    ]
    # StateFault directives run at the harness level too (a socket can't
    # reach device memory): SnapshotCorrupt flips one checksum-covered bit
    # in the target match's on-device ring row, CheckpointCorrupt flips a
    # bit in the newest on-disk server checkpoint. Both are seeded from
    # the plan so the injection is replayable.
    sdc_rng = np.random.RandomState(plan.seed ^ 0x5DC)
    snaps = [{"at": d.at, "target": d.target, "done": False}
             for d in plan.snapshot_corrupts()]
    ckcs = [{"at": d.at, "done": False}
            for d in plan.checkpoint_corrupts()]

    def inject_snapshot(d):
        if server is None:
            return False
        m = d["target"][1] if d["target"] is not None else 0
        h = handle_of.get(m)
        if h is None or h in server._lanes:
            return False
        core = server.groups[h.group]
        s = core.slots[h.slot]
        if not s.active:
            return False
        frames_h = np.asarray(core.rings.frames)[h.slot]
        # A mid-depth resident row: old enough that the save already
        # settled, young enough to survive until the next attest sweep.
        rows = np.flatnonzero(
            (frames_h >= 0) & (frames_h <= s.frame - 3)
            & (frames_h >= s.frame - 5)
        )
        if rows.size == 0:
            return False
        row = int(rows[0])
        core.rings, info = integrity.flip_ring_bit(
            core.rings, row, sdc_rng, slot=h.slot
        )
        faults.append((net.now, "snapshot_corrupt", info))
        return True
    recorders = (
        {"server": FlightRecorder(),
         **{m: FlightRecorder() for m in ext}}
        if obs_dir else {}
    )
    faults = []
    restore_frame = None
    for _ in range(n_iters):
        net.advance(FPS_DT)
        for k in kills:
            if not k["killed"] and net.now >= k["at"]:
                victim = ext.pop(k["me"])
                faults.extend(victim[0].socket.faults)
                victim[0].socket.close()
                k["killed"] = True
            elif k["killed"] and not k["done"] and net.now >= k["until"]:
                m = k["me"]
                fresh = make_ext_peer(net, m, plan, tap)
                fresh[2].begin_rejoin(("srv", m))
                ext[m] = fresh
                k["done"] = True
        for k in skrs:
            if not k["killed"] and net.now >= k["at"]:
                # kill -9: no flush, no farewell — sockets just go dark.
                # Harvest the dying host sessions' CRC-drop counts into the
                # (restart-surviving) Metrics first: chaos corruption is
                # tx-side on the ext sockets, so the server end is where
                # the v5 trailer check catches it.
                for match in server._matches.values():
                    for ep in match.session._endpoints.values():
                        if ep.data_crc_drops:
                            metrics.count("data_crc_drops", ep.data_crc_drops)
                    match.session.socket.close()
                server = None
                k["killed"] = True
            elif k["killed"] and not k["done"] and net.now >= k["until"]:
                server = build_server(ckpt_dir, capacity, groups, net,
                                      metrics, tracer, ledger)
                attachments = {
                    (h.group, h.slot): {
                        "session": make_host_session(net, m, tap),
                        "local_inputs": server_inputs,
                        "donor": ("ext", m),
                    }
                    for m, h in handle_of.items()
                }
                restored = server.checkpointer.restore(server, attachments)
                assert {(h.group, h.slot) for h in restored} == set(
                    attachments
                )
                restore_frame = max(
                    p[0].current_frame for p in ext.values()
                )
                k["done"] = True
        for d in snaps:
            if not d["done"] and net.now >= d["at"]:
                d["done"] = inject_snapshot(d)
        for d in ckcs:
            if not d["done"] and net.now >= d["at"]:
                ckpts = sorted(
                    f for f in os.listdir(ckpt_dir)
                    if f.startswith("server_ckpt_") and f.endswith(".npz")
                )
                if ckpts:
                    newest = max(
                        ckpts, key=lambda f: int(f[len("server_ckpt_"):-4])
                    )
                    info = integrity.flip_file_bit(
                        os.path.join(ckpt_dir, newest), sdc_rng
                    )
                    if info is not None:
                        faults.append((net.now, "checkpoint_corrupt", info))
                        d["done"] = True
        if server is not None:
            server.run_frame()
            if recorders:
                recorders["server"].capture(server=server, now=net.now)
        for m, peer in ext.items():
            ext_step(net, peer, canon if m == canon_match else None)
            if recorders:
                recorders[m].capture(
                    session=peer[0], runner=peer[1], supervisor=peer[2],
                    now=net.now,
                )
    for peer in ext.values():
        faults.extend(peer[0].socket.faults)
    if obs_dir:
        os.makedirs(obs_dir, exist_ok=True)
        for name, rec in recorders.items():
            rec.export_jsonl(
                os.path.join(obs_dir, f"serve_soak_{name}_frames.jsonl")
            )
        prov_paths = []
        for comp, log in prov.items():
            p = os.path.join(obs_dir, f"serve_soak_{comp}_provenance.jsonl")
            log.export_jsonl(p)
            prov_paths.append(p)
        trace_paths = []
        if server is not None:
            arts = server.export_telemetry(obs_dir, prefix="serve_soak")
            if arts and "trace" in arts:
                trace_paths.append(arts["trace"])
        if ledger is not None and "server" in prov:
            # Blamed-input flow arrows: re-emit each blamed entry keyed
            # by its causal rx input datagram so the merged trace draws
            # sender-tx -> server-rx -> spec_resim across process tracks.
            p = os.path.join(obs_dir, "serve_soak_spec_provenance.jsonl")
            if ledger.export_provenance(p, prov["server"]):
                prov_paths.append(p)
        merge_traces(
            trace_paths, prov_paths,
            path=os.path.join(obs_dir, "serve_soak_merged_trace.json"),
        )
    assert all(k["done"] for k in kills + skrs)
    return server, ext, handle_of, restore_frame, canon, faults, metrics


def assert_match_converged(server, handle, ext_peer, after_frame):
    """Server-side and external session agree bitwise on every settled
    checksum past ``after_frame``."""
    host = server._matches[handle].session
    assert host.current_state() == SessionState.RUNNING
    frames, rows = settled_checksums([host, ext_peer[0]])
    tail = [(f, r) for f, r in zip(frames, rows) if f > after_frame]
    assert len(tail) >= 2, f"match {handle}: no settled tail past {after_frame}"
    for f, row in tail:
        assert row[0] == row[1], f"match {handle} frame {f} diverged: {row}"


# ---------------------------------------------------------------------------
# Non-slow smoke: server kill -> checkpoint restart -> bitwise rejoin
# ---------------------------------------------------------------------------

SMOKE_PLAN = ChaosPlan(
    909,
    (
        LossBurst(1.0, 2.0, 0.2),
        Duplicate(1.5, 2.5, 0.2),
        ServerKillRestart(3.0, "server", 1.5),
    ),
)


def test_server_crash_restart_smoke(tmp_path):
    server, ext, handle_of, restore_frame, _, faults, metrics = (
        run_served_soak(
            SMOKE_PLAN, n_matches=2, n_iters=480, capacity=2, groups=1,
            ckpt_dir=str(tmp_path),
        )
    )
    assert server is not None and restore_frame is not None
    # Every match made it back onto the batch path, healthy.
    assert server.slots_active == 2 and not server._lanes
    for m, h in handle_of.items():
        assert server.health_of(h) is SlotHealth.HEALTHY
        assert_match_converged(server, h, ext[m], restore_frame)
        assert ext[m][2].health in (Health.HEALTHY, Health.DEGRADED)
    assert server.readmissions_total >= 2  # both rejoined via lanes
    assert server.evictions_total == 0
    assert server.cache_size() == 1
    assert any(k == "loss" for _, k, _ in faults)


def test_soak_exports_fleet_trace_artifacts(tmp_path, monkeypatch):
    """GGRS_OBS_DIR turns the soak into an artifact producer: flight
    recorder frames, per-component provenance logs, the server telemetry
    set (trace/metrics/SLO/HTML report), and one merged Perfetto trace —
    continuous across the server kill/restart."""
    import json

    obs = tmp_path / "obs"
    monkeypatch.setenv("GGRS_OBS_DIR", str(obs))
    run_served_soak(
        SMOKE_PLAN, n_matches=2, n_iters=330, capacity=2, groups=1,
        ckpt_dir=str(tmp_path / "ckpt"),
    )
    for f in (
        "serve_soak_server_frames.jsonl",
        "serve_soak_server_provenance.jsonl",
        "serve_soak_ext0_provenance.jsonl",
        "serve_soak_ext1_provenance.jsonl",
        "serve_soak_trace.json",
        "serve_soak_metrics.prom",
        "serve_soak_slo.json",
        "serve_soak_report.html",
        "serve_soak_spec_ledger.jsonl",
        "serve_soak_merged_trace.json",
    ):
        p = obs / f
        assert p.exists() and p.stat().st_size > 0, f"missing artifact {f}"
    with open(obs / "serve_soak_merged_trace.json") as f:
        merged = json.load(f)
    events = merged["traceEvents"]
    # Server span track AND all three wire tracks landed in one trace,
    # with cross-process flow arrows stitched between them.
    tracks = {
        ev["args"]["name"]
        for ev in events
        if ev.get("ph") == "M" and ev["name"] == "thread_name"
    }
    assert {"wire:server", "wire:ext0", "wire:ext1"} <= tracks
    assert "server" in tracks  # the tracer's serve-loop track
    flow_pids = {}
    for ev in events:
        if ev.get("cat") == "flow":
            flow_pids.setdefault(ev["id"], set()).add(ev["pid"])
    assert any(len(p) >= 2 for p in flow_pids.values())
    # The provenance timeline is continuous across the server restart:
    # records exist both before the kill (t=3.0) and after (t=4.5).
    kill_us, back_us = int(3.0e6), int(4.5e6)
    stamps = []
    with open(obs / "serve_soak_server_provenance.jsonl") as f:
        for line in f:
            rec = json.loads(line)
            if "meta" not in rec:
                stamps.append(rec["ts_us"])
    assert min(stamps) < kill_us and max(stamps) > back_us


# ---------------------------------------------------------------------------
# Acceptance: one frame's provenance spans peer / relay / server tracks
# ---------------------------------------------------------------------------


def test_served_relay_trace_spans_three_component_tracks(tmp_path):
    """A match whose server-hosted replica talks to its external peer
    THROUGH the relay tier, with passive sidecars on all three raw
    sockets: the merged trace carries wire tracks for peer, relay and
    server, and one input frame's flow chain crosses all three —
    tx at the originator, rx+tx at the relay, rx at the terminal."""
    net = LoopbackNetwork()
    logs = {}

    def tap(sock, component, pid):
        log = logs[component] = ProvenanceLog(
            component, pid=pid, clock=lambda: net.now
        )
        return SidecarSocket(sock, log)

    relay_tracer = SpanTracer(
        clock=lambda: net.now, pid=100, process_name="relay"
    )
    relay = RelayServer(
        tap(net.socket(("relay", 0)), "relay", 100),
        clock=lambda: net.now, tracer=relay_tracer,
    )

    def relay_session(me, component, pid):
        rsock = RelaySocket(
            tap(net.socket(("peer", me)), component, pid),
            [("relay", 0)], session_id=1, peer_id=me,
            clock=lambda: net.now,
        )
        builder = (
            SessionBuilder(box_game.INPUT_SPEC)
            .with_num_players(2)
            .with_max_prediction_window(MAX_PRED)
            .with_disconnect_timeout(1.0)
        )
        for h in range(2):
            builder.add_player(
                PlayerType.local() if h == me
                else PlayerType.remote(peer_addr(h)), h,
            )
        return builder.start_p2p_session(rsock, clock=lambda: net.now)

    tracer = SpanTracer(clock=lambda: net.now, pid=500,
                        process_name="server")
    server = build_server(
        str(tmp_path / "ckpt"), 1, 1, net, Metrics(), tracer
    )
    server.add_match(relay_session(0, "server", 500), server_inputs)
    ext_sess = relay_session(1, "ext", 600)
    ext_runner = RollbackRunner(
        box_game.make_schedule(), box_game.make_world(2).commit(),
        max_prediction=MAX_PRED, num_players=2,
        input_spec=box_game.INPUT_SPEC,
    )
    for _ in range(300):
        net.advance(FPS_DT)
        relay.pump(net.now)
        server.run_frame()
        ext_sess.poll_remote_clients()
        if ext_sess.current_state() != SessionState.RUNNING:
            continue
        for h in ext_sess.local_player_handles():
            ext_sess.add_local_input(
                h, scripted_input(h, ext_sess.current_frame)
            )
        try:
            ext_runner.handle_requests(ext_sess.advance_frame(), ext_sess)
        except PredictionThreshold:
            pass
    assert ext_sess.current_frame >= 150  # the match actually ran

    obs = tmp_path / "obs"
    os.makedirs(obs)
    prov_paths = []
    for comp, log in logs.items():
        p = str(obs / f"{comp}_provenance.jsonl")
        log.export_jsonl(p)
        prov_paths.append(p)
    relay_trace = str(obs / "relay_trace.json")
    relay_tracer.export_perfetto(relay_trace)
    arts = server.export_telemetry(str(obs), prefix="served_relay")
    merged = merge_traces(
        [arts["trace"], relay_trace], prov_paths,
        path=str(obs / "merged_trace.json"),
    )

    # Three component wire tracks plus both span tracers, one timeline.
    tracks = {
        ev["args"]["name"]
        for ev in merged["traceEvents"]
        if ev.get("ph") == "M" and ev["name"] == "thread_name"
    }
    assert {"wire:server", "wire:relay", "wire:ext"} <= tracks
    # Flow arrows cross at least three distinct merged processes.
    flow_pids = {}
    for ev in merged["traceEvents"]:
        if ev.get("cat") == "flow":
            flow_pids.setdefault(ev["id"], set()).add(ev["pid"])
    assert any(len(p) >= 3 for p in flow_pids.values())

    # One frame's provenance, followed end to end: originator tx ->
    # relay rx -> relay tx -> terminal rx, identical flow key throughout.
    spanning = None
    for frame in range(40, 90):
        for chain in frame_flows(prov_paths, frame).values():
            if {"server", "relay", "ext"} <= {c for c, _ in chain}:
                spanning = chain
                break
        if spanning:
            break
    assert spanning is not None, "no input frame crossed all three tracks"
    comps = [c for c, _ in spanning]
    dirs = [r["dir"] for _, r in spanning]
    assert comps[0] in ("server", "ext") and dirs[0] == "tx"
    assert comps[-1] in ("server", "ext") and dirs[-1] == "rx"
    i = comps.index("relay")
    assert comps[i:i + 2] == ["relay", "relay"]
    assert dirs[i:i + 2] == ["rx", "tx"]  # the relay forwarded verbatim


# ---------------------------------------------------------------------------
# The slow acceptance soak: S=16 under full chaos
# ---------------------------------------------------------------------------

# Corrupt windows are allowed everywhere since protocol v5: every
# data-plane frame (inputs included) carries a crc32 trailer, so a
# bit-flipped datagram never decodes — it is dropped and counted
# (``data_crc_drops``), indistinguishable from loss, which rollback
# already absorbs. The StateFault family rides along: one snapshot-ring
# bit flip on the batch (self-healed bitwise by the attestation sweep,
# quarantine-free) and one checkpoint-file bit flip while the server is
# down (the restore falls back to the next-newest clean checkpoint).
SOAK_PLAN = ChaosPlan(
    2025,
    (
        LossBurst(2.0, 4.0, 0.2),
        LossBurst(8.0, 10.0, 0.25),
        Reorder(3.0, 6.0, 0.2, delay=0.05),
        Duplicate(5.0, 7.0, 0.3),
        Corrupt(2.5, 9.5, 0.05),
        Partition(6.0, 6.5, src=("ext", 3)),
        KillRestart(4.0, ("ext", 0), 1.5),
        ServerKillRestart(11.0, "server", 1.5),
        SnapshotCorrupt(7.6, ("ext", 1)),
        CheckpointCorrupt(12.0, "server"),
    ),
)


@pytest.mark.slow
def test_serve_chaos_soak_s16(tmp_path):
    n = 16
    server, ext, handle_of, restore_frame, canon, faults, metrics = (
        run_served_soak(
            SOAK_PLAN, n_matches=n, n_iters=990, capacity=n, groups=4,
            ckpt_dir=str(tmp_path), canon_match=1,
        )
    )
    assert server is not None

    # Converged: every match back on the batch, both replicas RUNNING.
    assert server.slots_active == n and not server._lanes
    assert server.evictions_total == 0
    for m, h in handle_of.items():
        assert server.health_of(h) is SlotHealth.HEALTHY
        assert_match_converged(server, h, ext[m], restore_frame)

    # Zero desyncs, anywhere: the chaos was all network-level and every
    # replica's checksum votes stayed unanimous.
    for m, peer in ext.items():
        assert peer[3].counters["desyncs_detected"] == 0
        assert peer[2].health in (Health.HEALTHY, Health.DEGRADED)
    assert metrics.counters["desyncs_detected"] == 0

    # The killed external peer came back through a donor state transfer
    # served from the live batch slot (the facade donor path).
    assert ext[0][3].counters["recoveries"] >= 1
    assert metrics.counters["reconnects_initiated"] >= 1

    # Server crash-restart: every match rejoined through a recovery lane,
    # within the documented recovery bound, and churn never recompiled.
    assert server.readmissions_total >= n
    recoveries = [
        v for k, s in metrics.series.items()
        if k.startswith("slot_recovery_frames") for v in s
    ]
    assert all(v <= 600 for v in recoveries)
    assert server.cache_size() == 1

    # The plan actually injected chaos of every scripted kind — including
    # wire corruption and both StateFault flavors.
    kinds = {k for _, k, _ in faults}
    assert {
        "loss", "reorder", "duplicate", "corrupt", "partition",
        "snapshot_corrupt", "checkpoint_corrupt",
    } <= kinds

    # v5 data-plane integrity: corrupted datagrams were dropped-and-counted
    # at the endpoints (never decoded), on both sides of the wire.
    drops = sum(
        ep.data_crc_drops
        for peer in ext.values()
        for ep in peer[0]._endpoints.values()
    ) + sum(
        ep.data_crc_drops
        for m in server._matches.values()
        for ep in m.session._endpoints.values()
    ) + int(metrics.counters.get("data_crc_drops", 0))
    assert drops > 0

    # The snapshot bit flip was detected by the attestation sweep and
    # repaired bitwise, in place, quarantine-free — no fault escalation,
    # and the serial replay below proves the repaired match's checksums
    # are exactly what an uninterrupted run would have produced.
    assert metrics.counters["sdc_detected"] >= 1
    assert metrics.counters["sdc_repaired"] >= 1
    assert (metrics.counters["sdc_repaired_bitwise"]
            == metrics.counters["sdc_repaired"])
    assert metrics.counters.get("sdc_unrepairable", 0) == 0

    # The corrupted newest checkpoint was refused by the digest-guarded
    # loader; the restart restored from the next-newest clean one.
    assert server.checkpointer.load_fallbacks >= 1

    # Independent serial replay: rebuild match 1's trajectory from nothing
    # but its canonical confirmed-input log; the reported checksums must
    # be bitwise identical to what the live (batched, chaos-ridden,
    # crash-restarted) match recorded.
    sess = ext[1][0]
    upto = min(sess.confirmed_frame(), max(canon))
    assert upto > 600  # the log actually covers the match

    class Log:
        def __init__(self):
            self.seen = {}

        def wants_checksum(self, frame):
            return True

        def report_checksum(self, frame, cs):
            self.seen[frame] = int(cs)

    replay = RollbackRunner(
        box_game.make_schedule(), box_game.make_world(2).commit(),
        max_prediction=MAX_PRED, num_players=2,
        input_spec=box_game.INPUT_SPEC,
    )
    log = Log()
    for f in range(upto + 1):
        bits, status = canon[f]
        replay.handle_requests(
            [SaveGameState(f), AdvanceFrame(bits=bits, status=status)], log
        )
    # The session prunes its checksum map to a few exchange intervals
    # behind confirmed, so only the tail survives — which is still a full
    # end-to-end proof: the checksum at frame ~900 depends bitwise on
    # every one of the ~900 frames (and both restarts) before it.
    recorded = {
        f: cs for f, cs in sess._local_checksums.items() if f <= upto
    }
    assert len(recorded) >= 3
    for f, cs in recorded.items():
        assert log.seen[f] == cs, f"serial replay diverged at frame {f}"
