"""Test config: run everything on a virtual 8-device CPU mesh.

Must set the env vars before jax is imported anywhere — conftest is imported
first by pytest, so this is the single authoritative place.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
