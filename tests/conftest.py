"""Test config: run everything on a virtual 8-device CPU mesh.

This image's sitecustomize pre-imports jax and force-selects the remote-TPU
platform via ``jax.config.update("jax_platforms", ...)`` — which overrides
the ``JAX_PLATFORMS`` env var. So the env var alone is not enough: we must
(a) inject the virtual-device XLA flag before any backend initializes, and
(b) re-update the config back to cpu. Tests then never touch the TPU tunnel
and get a deterministic 8-device mesh for sharding coverage.

Set ``GGRS_TEST_TPU=1`` to run the suite against the real default backend
instead (Pallas kernels then execute compiled rather than interpreted;
multi-device sharding tests will skip if only one chip is visible).
"""

import os

if os.environ.get("GGRS_TEST_TPU") != "1":
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the suite's dominant cost is compiling
# per-test executables (every runner's schedule closure is a fresh jit
# entry), and the programs are identical across runs — a warm cache cuts
# attestation-heavy test files ~3x (measured 28 -> 10 s). Keyed by HLO
# hash, so stale entries are impossible; delete the dir to force cold.
# NOTE: must go through jax.config.update — sitecustomize imported jax
# before this file runs, so the env-var forms have already been read.
import jax  # noqa: E402  (re-import is a no-op; config still mutable)

jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/bevy_ggrs_tpu_jax_cache"),
)
jax.config.update(
    "jax_persistent_cache_min_entry_size_bytes",
    int(os.environ.get("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")),
)
jax.config.update(
    "jax_persistent_cache_min_compile_time_secs",
    float(os.environ.get("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")),
)
