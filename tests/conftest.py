"""Test config: run everything on a virtual 8-device CPU mesh.

This image's sitecustomize pre-imports jax and force-selects the remote-TPU
platform via ``jax.config.update("jax_platforms", ...)`` — which overrides
the ``JAX_PLATFORMS`` env var. So the env var alone is not enough: we must
(a) inject the virtual-device XLA flag before any backend initializes, and
(b) re-update the config back to cpu. Tests then never touch the TPU tunnel
and get a deterministic 8-device mesh for sharding coverage.

Set ``GGRS_TEST_TPU=1`` to run the suite against the real default backend
instead (Pallas kernels then execute compiled rather than interpreted;
multi-device sharding tests will skip if only one chip is visible).
"""

import os

if os.environ.get("GGRS_TEST_TPU") != "1":
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
