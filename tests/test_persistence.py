"""Disk checkpoint/resume: bitwise round-trips and crash-recovery e2e.

The key property: a session restored from disk continues producing the SAME
checksums as one that never stopped (integer state round-trips bitwise,
float leaves are exact host copies) — so resume is invisible to the
SyncTest determinism harness and to remote peers' desync detection.
"""

import numpy as np
import pytest

from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.runner import RollbackRunner
from bevy_ggrs_tpu.session import SyncTestSession
from bevy_ggrs_tpu.state import combine64, checksum, ring_init, ring_save
from bevy_ggrs_tpu.utils.persistence import (
    CheckpointManager,
    load_checkpoint,
    restore_runner,
    save_checkpoint,
    save_runner,
)


def test_world_state_round_trip_bitwise(tmp_path):
    state = box_game.make_world(2).commit()
    p = str(tmp_path / "w.npz")
    save_checkpoint(p, state, {"note": "hello"})
    restored, meta = load_checkpoint(p, box_game.make_world(2).commit())
    assert meta == {"note": "hello"}
    assert combine64(checksum(restored)) == combine64(checksum(state))


def test_ring_round_trip(tmp_path):
    state = box_game.make_world(2).commit()
    ring = ring_init(state, 4)
    ring, cs = ring_save(ring, state, 2)
    p = str(tmp_path / "r.npz")
    save_checkpoint(p, ring)
    restored, _ = load_checkpoint(p, ring_init(state, 4))
    assert int(restored.frames[2]) == 2
    assert combine64(restored.checksums[2]) == combine64(cs)


def test_template_mismatch_rejected(tmp_path):
    state = box_game.make_world(2).commit()
    p = str(tmp_path / "w.npz")
    save_checkpoint(p, state)
    # Different capacity → shape mismatch, loud failure.
    other = box_game.make_world(2, capacity=32).commit()
    with pytest.raises(ValueError, match="template"):
        load_checkpoint(p, other)
    # Different structure → path mismatch.
    with pytest.raises(ValueError, match="does not match template"):
        load_checkpoint(p, {"x": np.zeros(3)})


def _make_pair(num_players=2, check_distance=3, max_prediction=8,
               input_delay=0):
    session = SyncTestSession(
        num_players,
        box_game.INPUT_SPEC,
        check_distance=check_distance,
        max_prediction=max_prediction,
        input_delay=input_delay,
    )
    runner = RollbackRunner(
        box_game.make_schedule(),
        box_game.make_world(num_players).commit(),
        max_prediction=max_prediction,
        num_players=num_players,
        input_spec=box_game.INPUT_SPEC,
    )
    return session, runner


def _drive(session, runner, frames, seed_base=0, collect=None):
    for i in range(frames):
        for h in range(session.num_players):
            session.add_local_input(h, np.uint8((seed_base + i + h) % 16))
        runner.handle_requests(session.advance_frame(), session)
        if collect is not None:
            collect.append(combine64(checksum(runner.state)))


def test_crash_recovery_resumes_bitwise(tmp_path):
    # Run A: 30 frames straight through, recording post-frame checksums.
    sess_a, run_a = _make_pair()
    trace_a = []
    _drive(sess_a, run_a, 30, collect=trace_a)

    # Run B: 12 frames, checkpoint, "crash", restore into a FRESH session +
    # runner pair (nothing survives but the file), then the remaining 18
    # frames — exercising forced rollbacks across the crash boundary with
    # the restored session's input history.
    sess_b, run_b = _make_pair()
    trace_b = []
    _drive(sess_b, run_b, 12, collect=trace_b)
    p = str(tmp_path / "crash.npz")
    save_runner(p, run_b, {"who": "test"}, session=sess_b)

    sess_c, run_c = _make_pair()
    meta = restore_runner(p, run_c, session=sess_c)
    assert meta["who"] == "test"
    assert run_c.frame == run_b.frame
    assert sess_c.current_frame == sess_b.current_frame
    _drive(sess_c, run_c, 18, seed_base=12, collect=trace_b)

    # Same inputs → identical checksum stream, across the crash boundary.
    # (seed_base keeps the input schedule identical between runs.)
    sess_d, run_d = _make_pair()
    trace_d = []
    _drive(sess_d, run_d, 12, collect=trace_d)
    _drive(sess_d, run_d, 18, seed_base=12, collect=trace_d)
    assert trace_b == trace_d


def test_crash_recovery_with_input_delay(tmp_path):
    """With input_delay > 0 the queues hold confirmed inputs BEYOND
    current_frame (in-flight delayed inputs); resume must replay them, not
    gap-fill zeros."""
    sess_b, run_b = _make_pair(input_delay=2)
    trace_b = []
    # Non-repeating inputs so a dropped in-flight input changes checksums.
    _drive(sess_b, run_b, 12, collect=trace_b)
    p = str(tmp_path / "delay.npz")
    save_runner(p, run_b, session=sess_b)

    sess_c, run_c = _make_pair(input_delay=2)
    restore_runner(p, run_c, session=sess_c)
    _drive(sess_c, run_c, 18, seed_base=12, collect=trace_b)

    sess_d, run_d = _make_pair(input_delay=2)
    trace_d = []
    _drive(sess_d, run_d, 12, collect=trace_d)
    _drive(sess_d, run_d, 18, seed_base=12, collect=trace_d)
    assert trace_b == trace_d


def test_manager_rolls_and_restores(tmp_path):
    d = str(tmp_path / "ckpts")
    mgr = CheckpointManager(d, interval=5, keep=2)
    session, runner = _make_pair()
    saved = []
    for _ in range(20):
        for h in range(2):
            session.add_local_input(h, np.uint8(runner.frame % 16))
        runner.handle_requests(session.advance_frame(), session)
        path = mgr.maybe_save(runner, session=session)
        if path:
            saved.append(path)
    # Saved at frames 5, 10, 15, 20; pruned to the last 2.
    assert len(saved) == 4
    live = sorted(x[0] for x in mgr._checkpoints())
    assert live == [15, 20]

    fresh_sess, fresh = _make_pair()
    meta = mgr.restore_latest(fresh, session=fresh_sess)
    assert meta is not None and fresh.frame == 20
    assert fresh_sess.current_frame == session.current_frame
    assert combine64(checksum(fresh.state)) == combine64(checksum(runner.state))


def test_manager_restore_empty_dir(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "none"), interval=5)
    _, runner = _make_pair()
    assert mgr.restore_latest(runner) is None


def _rewrite_as_v1(path):
    """Stamp an on-disk checkpoint's header back to format version 1."""
    import json

    from bevy_ggrs_tpu.utils import persistence as P

    with np.load(path) as data:
        arrays = {k: data[k] for k in data.files}
    header = json.loads(bytes(arrays[P._HEADER_KEY]).decode())
    header["version"] = 1
    arrays[P._HEADER_KEY] = np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8
    )
    with open(path, "wb") as f:
        np.savez_compressed(f, **arrays)


def test_v1_pre_widening_checkpoint_rejected_with_explicit_error(tmp_path):
    """A checkpoint whose ring checksums are the pre-widening uint32[depth]
    (format v1, old layout) must fail with a message naming the
    incompatibility, not a generic per-leaf shape mismatch (ADVICE r2:
    restore_latest would otherwise walk every old checkpoint failing each
    one opaquely)."""
    from bevy_ggrs_tpu.utils import persistence as P

    path = str(tmp_path / "old.npz")
    old = {"ring": {"checksums": np.zeros((5,), np.uint32)}}
    new = {"ring": {"checksums": np.zeros((5, 2), np.uint32)}}
    P.save_checkpoint(path, old)
    _rewrite_as_v1(path)
    with pytest.raises(ValueError, match="predates 64-bit checksums"):
        P.load_checkpoint(path, new)


def test_v1_current_layout_checkpoint_still_loads(tmp_path):
    """The widening shipped before the format-version bump, so checkpoints
    written by that code are v1 WITH the current layout — they must load
    (code-review r3: a blanket v1 reject would strand every checkpoint
    saved by the previous HEAD)."""
    from bevy_ggrs_tpu.utils import persistence as P

    path = str(tmp_path / "mid.npz")
    tree = {"ring": {"checksums": np.arange(10, dtype=np.uint32).reshape(5, 2)}}
    P.save_checkpoint(path, tree)
    _rewrite_as_v1(path)
    loaded, _ = P.load_checkpoint(path, tree)
    assert np.array_equal(
        np.asarray(loaded["ring"]["checksums"]), tree["ring"]["checksums"]
    )
