"""Serve-tier fault domains: blast-radius containment contracts.

The acceptance gates for `serve/faults.py` + the MatchServer fault loop:

- Fault atomicity: a :class:`SlotFault` escaping a batched tick leaves
  EVERY slot — including the faulting one — bitwise untouched, and the
  round re-ticks cleanly without it.
- Typed faults: the blanket rejections the batch used to raise
  (NotImplementedError / ValueError) are now :class:`SlotFault` with a
  machine-readable reason, so the server can fence exactly one slot.
- Drain -> recover -> readmit is bitwise-continuous with the uninterrupted
  trajectory AND recompile-free (the churn contract extends to fault
  churn: all recovery lanes share one warmed rollout executable).
- The watchdog fences a deliberately-hung session within
  ``strike_limit`` frames; siblings keep their cadence.
- Crash-restart: a checkpointed server rebuilt from disk resumes every
  synctest match bitwise at its exact (group, slot).
"""

import numpy as np
import pytest

from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.runner import RollbackRunner
from bevy_ggrs_tpu.serve import (
    MatchServer,
    ServerCheckpointer,
    SlotFault,
    SlotHealth,
    SlotHealthFSM,
    SlotTicket,
)
from bevy_ggrs_tpu.serve.faults import adopt_ticket
from bevy_ggrs_tpu.session.builder import SessionBuilder
from bevy_ggrs_tpu.session.requests import RestoreGameState, SaveGameState
from bevy_ggrs_tpu.state import checksum, combine64
from bevy_ggrs_tpu.utils import xla_cache
from bevy_ggrs_tpu.utils.metrics import Metrics
from tests.test_batched_sessions import (
    BRANCHES,
    MAXPRED,
    P,
    SPEC_FRAMES,
    adv,
    assert_slot_equals_runner,
    drive,
    make_core,
    make_script,
    make_singleton,
)


def slot_cs(core, slot):
    return combine64(checksum(core.slot_state(slot)))


# ---------------------------------------------------------------------------
# Core-level: typed faults + atomicity
# ---------------------------------------------------------------------------


def test_slot_fault_reasons_are_typed():
    core = make_core(num_slots=2)
    slot = core.admit()
    with pytest.raises(SlotFault) as ei:
        core.tick({slot: ([RestoreGameState(0, None)], None, None)})
    assert (ei.value.slot, ei.value.reason) == (slot, "restore_request")
    with pytest.raises(SlotFault) as ei:
        core.tick({slot: ([SaveGameState(0)], None, None)})  # save, no adv
    assert ei.value.reason == "non_canonical_burst"
    too_deep = []
    for f in range(core.burst_frames + 1):
        too_deep += [SaveGameState(f), adv([1, 2])]
    with pytest.raises(SlotFault) as ei:
        core.tick({slot: (too_deep, None, None)})
    assert ei.value.reason == "burst_overflow"
    assert ei.value.frame == 0


def test_fault_leaves_every_slot_bitwise_untouched():
    """THE isolation regression: one slot's bad request list in a
    multi-slot round must not move ANY slot — not the siblings (whose
    work shared the aborted round) and not the faulter itself — and the
    round must re-tick cleanly without the faulted slot."""
    core = make_core(num_slots=3)
    a, b = core.admit(), core.admit()
    sa = make_script(seed=11, depth=2, cycles=2)
    sb = make_script(seed=12, depth=3, cycles=2)
    half = len(sb) // 2
    drive(core, {a: sa[: len(sa) // 2], b: sb[:half]})
    before = {
        s: (core.slots[s].frame, slot_cs(core, s),
            np.asarray(core.rings.checksums)[s].copy())
        for s in (a, b)
    }
    with pytest.raises(SlotFault) as ei:
        core.tick({
            a: ([adv([1, 2])], None, None),  # advance without save
            b: (sb[half][0], sb[half][1], None),
        })
    assert ei.value.slot == a
    for s in (a, b):
        frame, cs, ring_cs = before[s]
        assert core.slots[s].frame == frame
        assert slot_cs(core, s) == cs
        assert np.array_equal(np.asarray(core.rings.checksums)[s], ring_cs)
    # Drop the faulter, re-tick the survivor's same work, finish both
    # scripts: bitwise parity with uninterrupted singletons for BOTH.
    core.tick({b: (sb[half][0], sb[half][1], None)})
    drive(core, {a: sa[len(sa) // 2:], b: sb[half + 1:]})
    for s, script in ((a, sa), (b, sb)):
        spec = make_singleton(spec=True)
        for reqs, confirmed in script:
            spec.tick(reqs, confirmed, None)
        assert_slot_equals_runner(core, s, spec)


def test_extract_readmit_bitwise_and_recompile_free():
    """Drain a slot mid-trajectory to a ticket, route it through a
    singleton runner (the recovery-lane move), readmit at the same traced
    slot index, finish the script: bitwise parity with the uninterrupted
    run and ZERO compiles through the whole churn."""
    assert xla_cache.install_compile_listeners()
    core = make_core(num_slots=2)
    s = core.admit()
    script = make_script(seed=21, depth=3, cycles=4)
    third = len(script) // 3
    drive(core, {s: script[:third]})
    # Lane stand-in, pre-warmed: the server warms its shared lane
    # executable at warmup() time, so it's outside the churn window.
    runner = make_singleton(spec=False)
    base = xla_cache.compile_counters()["backend_compiles"]
    cache0 = core._exec.cache_size()

    ticket = core.extract(s)
    assert not core.slots[s].active
    adopt_ticket(runner, ticket)
    for reqs, _ in script[third: 2 * third]:
        runner.handle_requests(reqs, None)
    back = SlotTicket(
        frame=runner.frame, state=runner.state, ring=runner.ring,
        input_log=dict(runner._input_log or {}),
    )
    assert core.admit(slot=s, ticket=back) == s
    drive(core, {s: script[2 * third:]})

    assert xla_cache.compile_counters()["backend_compiles"] == base
    assert core._exec.cache_size() == cache0 == 1
    spec = make_singleton(spec=True)
    for reqs, confirmed in script:
        spec.tick(reqs, confirmed, None)
    assert_slot_equals_runner(core, s, spec)


def test_slot_health_fsm_legality():
    fsm = SlotHealthFSM(0, strike_limit=3)
    assert fsm.state is SlotHealth.HEALTHY
    # Strike path: degrade on the first miss, trip at the limit.
    assert not fsm.strike(10)
    assert fsm.state is SlotHealth.DEGRADED
    fsm.clear()  # one good tick forgives the streak
    assert (fsm.state, fsm.strikes) == (SlotHealth.HEALTHY, 0)
    assert not fsm.strike(11) and not fsm.strike(12)
    assert fsm.strike(13)
    fsm.to(SlotHealth.QUARANTINED, reason="watchdog_timeout", frame=13)
    assert fsm.last_reason == "watchdog_timeout"
    assert fsm.last_fault_frame == 13 and fsm.strikes == 0
    with pytest.raises(ValueError):
        fsm.to(SlotHealth.HEALTHY)  # must pass through RECOVERING
    fsm.to(SlotHealth.RECOVERING)
    fsm.to(SlotHealth.HEALTHY)
    fsm.to(SlotHealth.QUARANTINED)
    fsm.to(SlotHealth.EVICTED)
    for state in SlotHealth:
        if state is SlotHealth.EVICTED:
            continue
        with pytest.raises(ValueError):
            fsm.to(state)  # EVICTED is terminal


# ---------------------------------------------------------------------------
# MatchServer: quarantine -> lane -> readmit
# ---------------------------------------------------------------------------


def make_server(metrics=None, clock=None, **kw):
    kw.setdefault("capacity", 4)
    kw.setdefault("stagger_groups", 2)
    if clock is not None:
        kw["clock"] = clock
    server = MatchServer(
        box_game.make_schedule(), box_game.make_world(P).commit(),
        MAXPRED, P, box_game.INPUT_SPEC,
        num_branches=BRANCHES, spec_frames=SPEC_FRAMES, metrics=metrics,
        **kw,
    )
    server.warmup()
    return server


def make_synctest():
    return (
        SessionBuilder(box_game.INPUT_SPEC)
        .with_num_players(P)
        .with_max_prediction_window(MAXPRED)
        .with_check_distance(2)
        .start_synctest_session()
    )


def inputs_for(seed):
    def f(frame, handle):
        return np.uint8((frame * 3 + handle * 5 + seed) % 16)

    return f


class FlakySession:
    """Delegating wrapper whose advance_frame raises exactly once, BEFORE
    the inner session moves — the injected 'session crashed' fault."""

    def __init__(self, inner, fail_at):
        self._inner = inner
        self._fail_at = fail_at
        self.failed = False

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def advance_frame(self):
        if not self.failed and self._inner.current_frame == self._fail_at:
            self.failed = True
            raise RuntimeError("injected session crash")
        return self._inner.advance_frame()


class HungSession:
    """Delegating wrapper that burns fake-clock time inside advance_frame
    for a window of frames — the deliberately-hung session the watchdog
    must fence."""

    def __init__(self, inner, clk, hang_frames, hang_s=0.2):
        self._inner = inner
        self._clk = clk
        self._hang = set(hang_frames)
        self._hang_s = hang_s

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def advance_frame(self):
        if self._inner.current_frame in self._hang:
            self._clk[0] += self._hang_s
        return self._inner.advance_frame()


def test_server_session_error_isolated_readmitted_no_recompile():
    """A session that raises mid-match is quarantined, recovers on a lane,
    readmits at its reserved slot — and the WHOLE incident is invisible:
    every match (faulted included) ends bitwise identical to a fault-free
    control server, with a compile-counter delta of zero."""
    from bevy_ggrs_tpu.obs.recorder import FlightRecorder

    assert xla_cache.install_compile_listeners()
    metrics = Metrics()
    server = make_server(metrics=metrics)
    control = make_server()
    handles = [
        server.add_match(FlakySession(make_synctest(), fail_at=5),
                         inputs_for(9)),
        server.add_match(make_synctest(), inputs_for(1)),
        server.add_match(make_synctest(), inputs_for(2)),
    ]
    c_handles = [
        control.add_match(make_synctest(), inputs_for(9)),
        control.add_match(make_synctest(), inputs_for(1)),
        control.add_match(make_synctest(), inputs_for(2)),
    ]
    for _ in range(4):
        server.run_frame()
        control.run_frame()
    base = xla_cache.compile_counters()["backend_compiles"]
    rec = FlightRecorder()
    recovering_seen = 0
    for _ in range(11):
        server.run_frame()
        control.run_frame()
        recovering_seen += rec.capture(server=server).slots_recovering
    assert server.faults_total == 1
    assert server.readmissions_total == 1
    assert recovering_seen >= 1  # the gauge column actually moved
    assert server.last_recovery_frames is not None
    assert 0 < server.last_recovery_frames <= 8
    assert metrics.counters["slot_faults"] == 1
    bad = server._matches[handles[0]]
    assert bad.fsm.state is SlotHealth.HEALTHY
    assert bad.fsm.last_reason == "session_error"
    # Bitwise vs the fault-free control, every match, same frame count.
    assert xla_cache.compile_counters()["backend_compiles"] == base
    assert server.cache_size() == 1
    for h, c in zip(handles, c_handles):
        core, ctrl = server.groups[h.group], control.groups[c.group]
        assert core.slots[h.slot].frame == ctrl.slots[c.slot].frame == 15
        assert slot_cs(core, h.slot) == slot_cs(ctrl, c.slot)


def test_server_watchdog_fences_hung_session():
    """A session that blows its host-tick budget ``strike_limit`` frames
    running gets DEGRADED strikes, then quarantined with its in-hand
    requests riding to the lane — while the healthy sibling never misses a
    frame. A single slow tick (one strike, then clean) is forgiven."""
    clk = [0.0]
    metrics = Metrics()
    server = make_server(metrics=metrics, clock=lambda: clk[0],
                         watchdog_budget_ms=50.0, watchdog_strike_limit=3)
    hung = server.add_match(
        HungSession(make_synctest(), clk, hang_frames={4, 5, 6}),
        inputs_for(3),
    )
    blip = server.add_match(
        HungSession(make_synctest(), clk, hang_frames={2}), inputs_for(4)
    )
    ok = server.add_match(make_synctest(), inputs_for(5))
    for _ in range(4):
        server.run_frame()
    assert server.health_of(hung) is SlotHealth.HEALTHY
    server.run_frame()  # frame 4: first miss -> DEGRADED
    assert server.health_of(hung) is SlotHealth.DEGRADED
    assert server.faults_total == 0
    for _ in range(7):
        server.run_frame()
    assert server.faults_total == 1
    assert server.readmissions_total == 1
    m = server._matches[hung]
    assert m.fsm.state is SlotHealth.HEALTHY
    assert m.fsm.last_reason == "watchdog_timeout"
    strikes = sum(
        v for k, v in metrics.counters.items()
        if k.startswith("watchdog_strikes")
    )
    assert strikes >= 4
    # One slow tick never faulted: strike -> clean wiped the streak.
    assert server.health_of(blip) is SlotHealth.HEALTHY
    # The healthy sibling kept full cadence through the incident.
    assert server.groups[ok.group].slots[ok.slot].frame == 12
    # The hung match lost no frames either: its in-flight requests rode
    # to the lane (pending) so session and runner stayed converged.
    sess = server._matches[hung].session
    assert sess.current_frame >= 12


def test_server_suspend_resume_same_match():
    """Voluntary drain of THE SAME match: suspend_match hands back a
    ticket, other matches keep running, resume_match readmits it (same
    session object) and it finishes bitwise where an uninterrupted match
    with the same input script would."""
    server = make_server()
    ref = make_server()
    sess = make_synctest()
    h = server.add_match(sess, inputs_for(7))
    other = server.add_match(make_synctest(), inputs_for(8))
    r = ref.add_match(make_synctest(), inputs_for(7))
    for _ in range(6):
        server.run_frame()
        ref.run_frame()
    ticket = server.suspend_match(h)
    assert ticket.frame == 6
    assert server.slots_active == 1
    for _ in range(4):
        server.run_frame()  # the other match runs on while h is parked
    h2 = server.resume_match(sess, inputs_for(7), ticket)
    for _ in range(6):
        server.run_frame()
        ref.run_frame()
    core = server.groups[h2.group]
    assert core.slots[h2.slot].frame == 12
    assert ref.groups[r.group].slots[r.slot].frame == 12
    assert slot_cs(core, h2.slot) == slot_cs(ref.groups[r.group], r.slot)
    assert server.groups[other.group].slots[other.slot].frame == 16


def test_server_retire_then_fresh_admit_reuses_slot():
    """retire_match -> add_match cycles a slot: the newcomer starts at
    frame 0 with none of the retired match's log/spec state leaking."""
    server = make_server(capacity=2, stagger_groups=1)
    ref = make_server(capacity=2, stagger_groups=1)
    h0 = server.add_match(make_synctest(), inputs_for(1))
    for _ in range(9):
        server.run_frame()
    server.retire_match(h0)
    assert server.slots_active == 0 and server.slots_free == 2
    h1 = server.add_match(make_synctest(), inputs_for(2))
    assert h1.slot == h0.slot  # the freed slot is handed out again
    r = ref.add_match(make_synctest(), inputs_for(2))
    for _ in range(9):
        server.run_frame()
        ref.run_frame()
    core = server.groups[h1.group]
    assert core.slots[h1.slot].frame == 9
    assert slot_cs(core, h1.slot) == slot_cs(ref.groups[r.group], r.slot)


# ---------------------------------------------------------------------------
# Crash-restart checkpoints
# ---------------------------------------------------------------------------


def test_checkpointer_save_restore_bitwise(tmp_path):
    """kill -9 drill for synctest matches: run a checkpointing server,
    drop it, rebuild from construction parameters + the newest checkpoint,
    and (a) every match resumes at its exact (group, slot) with bitwise-
    identical state, (b) the resumed trajectory stays bitwise equal to an
    uninterrupted reference run."""
    ckpt = str(tmp_path / "ckpts")
    server = make_server(checkpoint_dir=ckpt, checkpoint_interval=6,
                         checkpoint_keep=2)
    ref = make_server()
    seeds = (11, 12, 13)
    handles = [server.add_match(make_synctest(), inputs_for(k))
               for k in seeds]
    r_handles = [ref.add_match(make_synctest(), inputs_for(k))
                 for k in seeds]
    for _ in range(12):
        server.run_frame()
        ref.run_frame()
    assert server.checkpointer.saves_total == 2  # frames 6 and 12
    want = {
        h: (server.groups[h.group].slots[h.slot].frame,
            slot_cs(server.groups[h.group], h.slot))
        for h in handles
    }
    del server  # the crash

    revived = make_server(checkpoint_dir=ckpt, checkpoint_interval=6,
                          checkpoint_keep=2)
    attachments = {
        (h.group, h.slot): {"session": make_synctest(),
                            "local_inputs": inputs_for(k)}
        for h, k in zip(handles, seeds)
    }
    restored = revived.checkpointer.restore(revived, attachments)
    assert {(h.group, h.slot) for h in restored} == set(attachments)
    for h in handles:
        frame, cs = want[h]
        core = revived.groups[h.group]
        assert core.slots[h.slot].frame == frame == 12
        assert slot_cs(core, h.slot) == cs
    for _ in range(6):
        revived.run_frame()
        ref.run_frame()
    for h, r in zip(handles, r_handles):
        core, rc = revived.groups[h.group], ref.groups[r.group]
        assert core.slots[h.slot].frame == rc.slots[r.slot].frame == 18
        assert slot_cs(core, h.slot) == slot_cs(rc, r.slot)


def test_checkpoint_records_portable_across_server_instances(tmp_path):
    """Snapshot portability property (the fleet failover precondition):
    a match record saved by one server restores BITWISE on a server
    instance that shares nothing with the source but the world template —
    different slot index, different stagger group, different batch width
    (hence a different compiled executor) — via both transports: the
    on-disk checkpoint loader and the pack/unpack migration blob."""
    import io

    from bevy_ggrs_tpu.serve import (
        load_checkpoint_matches,
        pack_match_record,
        unpack_match_record,
    )
    from bevy_ggrs_tpu.state import checksum, combine64

    ckpt = str(tmp_path / "ckpts")
    # Source: 2 groups x 2 slots. Destination: 3 groups x 1 slot — every
    # match necessarily lands at a different (group, slot) with a
    # different per-group batch width.
    src = make_server(checkpoint_dir=ckpt, checkpoint_interval=6)
    ref = make_server()
    seeds = (31, 32, 33)
    handles = [src.add_match(make_synctest(), inputs_for(k)) for k in seeds]
    r_handles = [ref.add_match(make_synctest(), inputs_for(k))
                 for k in seeds]
    for _ in range(12):
        src.run_frame()
        ref.run_frame()
    want = {
        (h.group, h.slot): slot_cs(src.groups[h.group], h.slot)
        for h in handles
    }
    # Migration-blob transport: pack one live match, unpack, and the
    # decoded ticket is bitwise the slot it came from.
    codec = src.state_codec()
    snap = src.snapshot_matches()[0]
    blob = pack_match_record(codec, snap)
    rec = unpack_match_record(codec, blob)
    assert rec["frame"] == 12 and rec["kind"] == "synctest"
    assert combine64(checksum(rec["ticket"].state)) == want[
        (snap["handle"].group, snap["handle"].slot)
    ]
    # Tampered state payload -> digest rejection, never a plausible world.
    with np.load(io.BytesIO(blob)) as npz:
        arrays = {k: npz[k] for k in npz.files}
    arrays["m0_state"] = arrays["m0_state"].copy()
    arrays["m0_state"][0] ^= 0xFF
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    with pytest.raises(ValueError, match="digest"):
        unpack_match_record(codec, buf.getvalue())

    path = src.checkpointer.latest()
    del src  # the source instance is gone; only disk + template remain

    dst = make_server(capacity=3, stagger_groups=3)
    key_to_seed = {(h.group, h.slot): k for h, k in zip(handles, seeds)}
    moved = {}
    for r in load_checkpoint_matches(path, dst.state_codec()):
        sess = make_synctest()
        sess.load_state_dict(r["session_state"])
        h = dst.resume_match(
            sess, inputs_for(key_to_seed[r["key"]]), r["ticket"]
        )
        assert dst.groups[h.group].slots[h.slot].frame == 12
        assert slot_cs(dst.groups[h.group], h.slot) == want[r["key"]]
        moved[r["key"]] = h
    # The resumed trajectories stay bitwise equal to the uninterrupted
    # reference on the foreign executor.
    for _ in range(6):
        dst.run_frame()
        ref.run_frame()
    for (h, r), k in zip(zip(handles, r_handles), seeds):
        d = moved[(h.group, h.slot)]
        assert dst.groups[d.group].slots[d.slot].frame == 18
        assert slot_cs(dst.groups[d.group], d.slot) == slot_cs(
            ref.groups[r.group], r.slot
        )


def test_checkpointer_guards(tmp_path):
    server = make_server(checkpoint_dir=str(tmp_path), checkpoint_interval=4)
    server.add_match(make_synctest(), inputs_for(1))
    for _ in range(4):
        server.run_frame()
    path = server.checkpointer.latest()
    assert path is not None
    fresh = make_server(checkpoint_dir=str(tmp_path))
    with pytest.raises(ValueError, match="no attachment"):
        fresh.checkpointer.restore(fresh, {})
    with pytest.raises(ValueError):
        ServerCheckpointer(str(tmp_path), interval=0)
    # Rolling window: old checkpoints are pruned to ``keep``.
    server2 = make_server(checkpoint_dir=str(tmp_path / "k"),
                          checkpoint_interval=2, checkpoint_keep=2)
    server2.add_match(make_synctest(), inputs_for(2))
    for _ in range(8):
        server2.run_frame()
    assert server2.checkpointer.saves_total == 4
    assert len(server2.checkpointer._checkpoints()) == 2
