"""SpectatorSession e2e: host P2P pair + spectator, over loopback.

Reference behavior being replicated: spectators receive confirmed inputs
from a host, never contribute input, never roll back
(`/root/reference/src/ggrs_stage.rs:195-211`,
`examples/box_game/box_game_spectator.rs`).
"""

import numpy as np

from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.runner import RollbackRunner
from bevy_ggrs_tpu.session import (
    PredictionThreshold,
    PlayerType,
    SessionBuilder,
    SessionState,
)
from bevy_ggrs_tpu.transport.loopback import LoopbackNetwork
from tests.test_p2p import FPS_DT, drive, make_pair, scripted_input


def make_spectator(net, host_addr, num_players=2):
    sock = net.socket(("spec", 0))
    session = (
        SessionBuilder(box_game.INPUT_SPEC)
        .with_num_players(num_players)
        .start_spectator_session(host_addr, sock, clock=lambda: net.now)
    )
    runner = RollbackRunner(
        box_game.make_schedule(),
        box_game.make_world(num_players).commit(),
        max_prediction=8,
        num_players=num_players,
        input_spec=box_game.INPUT_SPEC,
    )
    return session, runner


def drive_spectator(session, runner):
    session.poll_remote_clients()
    if session.current_state() != SessionState.RUNNING:
        return
    try:
        requests = session.advance_frame()
    except PredictionThreshold:
        return
    runner.handle_requests(requests, session)


class TestSpectator:
    def test_spectator_follows_host(self):
        net = LoopbackNetwork()
        peers = make_pair(net, spectators=[("spec", 0)])
        spec_session, spec_runner = make_spectator(net, ("peer", 0))

        for _ in range(120):
            net.advance(FPS_DT)
            for session, runner in peers:
                session.poll_remote_clients()
                if session.current_state() != SessionState.RUNNING:
                    continue
                for h in session.local_player_handles():
                    session.add_local_input(h, scripted_input(h, session.current_frame))
                try:
                    runner.handle_requests(session.advance_frame(), session)
                except PredictionThreshold:
                    pass
            drive_spectator(spec_session, spec_runner)

        # Spectator advanced a meaningful number of confirmed frames.
        assert spec_runner.frame >= 40
        # Spectator never rolled back (`run_spectator` never emits loads).
        assert spec_runner.rollbacks_total == 0

        # Its world at frame F must equal the true confirmed trajectory at F:
        # both players' inputs are a deterministic script, so replay them
        # through a fresh serial run and compare translations bitwise.
        ref = RollbackRunner(
            box_game.make_schedule(),
            box_game.make_world(2).commit(),
            max_prediction=8,
            num_players=2,
            input_spec=box_game.INPUT_SPEC,
        )
        from bevy_ggrs_tpu.session.requests import AdvanceFrame

        for f in range(spec_runner.frame):
            bits = np.stack([scripted_input(h, f) for h in range(2)])
            ref.handle_requests(
                [AdvanceFrame(bits=bits, status=np.zeros(2, np.int32))]
            )
        a = spec_runner.world()["components"]["translation"]
        b = ref.world()["components"]["translation"]
        np.testing.assert_array_equal(a, b)

    def test_spectator_waits_without_host_data(self):
        net = LoopbackNetwork()
        # Host exists but never sends inputs (no local advance).
        peers = make_pair(net, spectators=[("spec", 0)])
        spec_session, spec_runner = make_spectator(net, ("peer", 0))
        # Let sync complete (host polls, spectator polls).
        for _ in range(20):
            net.advance(FPS_DT)
            for session, _ in peers:
                session.poll_remote_clients()
            spec_session.poll_remote_clients()
        assert spec_session.current_state() == SessionState.RUNNING
        try:
            spec_session.advance_frame()
            advanced = True
        except PredictionThreshold:
            advanced = False
        assert not advanced
        assert spec_runner.frame == 0

    def test_spectator_acks_bound_host_pending(self):
        """Regression: spectators must ack received inputs, else the host's
        per-spectator unacked span grows O(frames) and eventually overflows
        the wire format's uint16 span length."""
        net = LoopbackNetwork()
        peers = make_pair(net, spectators=[("spec", 0)])
        spec_session, spec_runner = make_spectator(net, ("peer", 0))
        for _ in range(150):
            net.advance(FPS_DT)
            for session, runner in peers:
                session.poll_remote_clients()
                if session.current_state() != SessionState.RUNNING:
                    continue
                for h in session.local_player_handles():
                    session.add_local_input(h, scripted_input(h, session.current_frame))
                try:
                    runner.handle_requests(session.advance_frame(), session)
                except PredictionThreshold:
                    pass
            drive_spectator(spec_session, spec_runner)
        host_session, _ = peers[0]
        pending = host_session._endpoints[("spec", 0)]._pending_output
        worst = max((len(d) for d in pending.values()), default=0)
        assert worst < 20, f"host pending to spectator grew to {worst} frames"

    def test_spectator_contributes_no_input(self):
        net = LoopbackNetwork()
        spec_session, _ = make_spectator(net, ("peer", 0))
        assert spec_session.local_player_handles() == []

    def test_catchup_burst_is_hard_capped_per_call(self):
        """A spectator hundreds of frames behind (shed/partition resume)
        must converge over several polls, never one unbounded dispatch
        burst — ``CATCHUP_BURST_CAP`` binds even a huge
        ``max_frames_behind``."""
        from bevy_ggrs_tpu.session.endpoint import PeerState
        from bevy_ggrs_tpu.session.spectator import (
            CATCHUP_BURST_CAP,
            SpectatorSession,
        )

        net = LoopbackNetwork()
        session = SpectatorSession(
            2,
            box_game.INPUT_SPEC,
            net.socket(("spec", 9)),
            ("peer", 0),
            max_frames_behind=10_000,
            clock=lambda: net.now,
        )
        session._endpoint.state = PeerState.RUNNING
        for h in range(2):
            for f in range(500):
                session._queues[h].add_input(f, scripted_input(h, f))

        requests = session.advance_frame()
        assert len(requests) == CATCHUP_BURST_CAP
        assert session.current_frame == CATCHUP_BURST_CAP
        # Repeated calls drain the backlog in bounded slices.
        total = len(requests)
        while session.current_frame < 499:
            batch = session.advance_frame()
            assert 1 <= len(batch) <= CATCHUP_BURST_CAP
            total += len(batch)
        assert total == session.current_frame
