"""Speculative branch engine: correctness vs. serial, sharding equivalence.

The north-star component (survey §2.3): B candidate input branches × F
frames as one vmapped rollout, branch axis sharded over the device mesh.
Every branch must be bit-identical to the serial single-branch execution of
the same inputs — speculation is an optimization, never a semantic change.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.parallel.sharding import branch_mesh, shard_branch_axis
from bevy_ggrs_tpu.parallel.speculate import (
    SpeculativeExecutor,
    bitmask_sampler,
    enumerate_branches,
    match_branch,
    merge_rings,
)
from bevy_ggrs_tpu.rollout import RolloutExecutor, advance_n
from bevy_ggrs_tpu.state import ring_init

B, F, P = 8, 4, 2


def setup():
    schedule = box_game.make_schedule()
    state = box_game.make_world(P).commit()
    rng = np.random.RandomState(3)
    bits = jnp.asarray(rng.randint(0, 16, (B, F, P), dtype=np.uint8))
    return schedule, state, bits


class TestEnumerate:
    def test_branch0_is_repeat_last(self):
        key = jax.random.PRNGKey(0)
        last = jnp.asarray([5, 9], dtype=jnp.uint8)
        bits = enumerate_branches(key, last, 16, 6, sampler=bitmask_sampler())
        assert bits.shape == (16, 6, 2)
        np.testing.assert_array_equal(
            np.asarray(bits[0]), np.broadcast_to(np.array([5, 9]), (6, 2))
        )

    def test_branches_differ(self):
        key = jax.random.PRNGKey(1)
        last = jnp.zeros((2,), jnp.uint8)
        bits = np.asarray(
            enumerate_branches(key, last, 32, 8, sampler=bitmask_sampler())
        )
        assert len({b.tobytes() for b in bits}) > 16


class TestMatch:
    def test_exact_match(self):
        bits = np.zeros((4, 5, 2), np.uint8)
        bits[2, :, 0] = 7
        confirmed = bits[2, :3]
        branch, depth = match_branch(bits, confirmed)
        assert branch == 2 and depth == 3

    def test_partial_match_prefers_deepest(self):
        bits = np.zeros((3, 5, 2), np.uint8)
        bits[1, 0, 0] = 1  # branch 1 wrong at frame 0
        bits[2, 2, 0] = 9  # branch 2 wrong at frame 2
        confirmed = np.zeros((4, 2), np.uint8)
        branch, depth = match_branch(bits, confirmed)
        assert branch == 0 and depth == 4  # branch 0 fully agrees

    def test_no_confirmed_frames(self):
        bits = np.zeros((4, 5, 2), np.uint8)
        assert match_branch(bits, np.zeros((0, 2), np.uint8)) == (0, 0)


class TestSpeculativeExecutor:
    def test_matches_serial_rollout_bitwise(self):
        schedule, state, bits = setup()
        ex = SpeculativeExecutor(schedule, B, F)
        result = ex.run(state, 0, bits)
        serial = RolloutExecutor(schedule, F)
        for b in range(B):
            ring0 = ring_init(state, F)
            ring, end_state, checksums = serial.run(
                ring0, state, 0, np.asarray(bits[b]),
                np.zeros((F, P), np.int32), n_frames=F,
            )
            spec_t = np.asarray(result.states.components["translation"][b])
            ser_t = np.asarray(end_state.components["translation"])
            np.testing.assert_array_equal(spec_t, ser_t)
            np.testing.assert_array_equal(
                np.asarray(result.checksums[b]), np.asarray(checksums)
            )

    def test_commit_selects_branch(self):
        schedule, state, bits = setup()
        ex = SpeculativeExecutor(schedule, B, F)
        result = ex.run(state, 0, bits)
        ring, end_state = ex.commit(result, 3)
        np.testing.assert_array_equal(
            np.asarray(end_state.components["translation"]),
            np.asarray(result.states.components["translation"][3]),
        )
        assert int(end_state.resources["frame_count"]) == F
        np.testing.assert_array_equal(
            np.asarray(ring.frames), np.arange(F, dtype=np.int32)
        )

    def test_merge_rings_overlays_saved_slots(self):
        schedule, state, bits = setup()
        ex = SpeculativeExecutor(schedule, B, F)
        result = ex.run(state, 0, bits)
        ring, _ = ex.commit(result, 1)
        main = ring_init(state, F)
        merged = merge_rings(main, ring)
        np.testing.assert_array_equal(np.asarray(merged.frames), np.asarray(ring.frames))

    def test_speculation_covers_confirmed_path(self):
        """The whole point: when confirmed inputs match a branch, committing
        it equals having simulated serially with those inputs."""
        schedule, state, bits = setup()
        ex = SpeculativeExecutor(schedule, B, F)
        result = ex.run(state, 0, bits)
        confirmed = np.asarray(bits)[5]  # pretend branch 5 was reality
        branch, depth = match_branch(np.asarray(bits), confirmed)
        assert depth == F
        _, end_state = ex.commit(result, branch)
        truth = advance_n(schedule, state, jnp.asarray(confirmed))
        np.testing.assert_array_equal(
            np.asarray(end_state.components["translation"]),
            np.asarray(truth.components["translation"]),
        )


class TestSharded:
    def test_sharded_equals_unsharded(self):
        schedule, state, _ = setup()
        n_dev = len(jax.devices())
        if n_dev < 2:
            pytest.skip("sharding test needs >1 device "
                        "(GGRS_TEST_TPU run on one chip)")
        mesh = branch_mesh()
        bb = 2 * n_dev
        rng = np.random.RandomState(11)
        bits = jnp.asarray(rng.randint(0, 16, (bb, F, P), dtype=np.uint8))

        plain = SpeculativeExecutor(schedule, bb, F)
        res_plain = plain.run(state, 0, bits)

        sharded = SpeculativeExecutor(schedule, bb, F, mesh=mesh)
        res_shard = sharded.run(state, 0, shard_branch_axis(bits, mesh))

        np.testing.assert_array_equal(
            np.asarray(res_plain.states.components["translation"]),
            np.asarray(res_shard.states.components["translation"]),
        )
        np.testing.assert_array_equal(
            np.asarray(res_plain.checksums), np.asarray(res_shard.checksums)
        )

    def test_sharded_commit_gathers(self):
        schedule, state, _ = setup()
        if len(jax.devices()) < 2:
            pytest.skip("sharding test needs >1 device")
        mesh = branch_mesh()
        bb = 16
        rng = np.random.RandomState(12)
        bits = jnp.asarray(rng.randint(0, 16, (bb, F, P), dtype=np.uint8))
        ex = SpeculativeExecutor(schedule, bb, F, mesh=mesh)
        result = ex.run(state, 0, shard_branch_axis(bits, mesh))
        ring, end_state = ex.commit(result, 13)
        truth = advance_n(schedule, state, bits[13])
        np.testing.assert_array_equal(
            np.asarray(end_state.components["translation"]),
            np.asarray(truth.components["translation"]),
        )


class TestGraftEntry:
    def test_entry_compiles_and_runs(self):
        import importlib, sys

        sys.path.insert(0, "/root/repo")
        mod = importlib.import_module("__graft_entry__")
        fn, args = mod.entry()
        out = jax.jit(fn)(*args)
        jax.block_until_ready(out)

    def test_dryrun_multichip(self):
        import importlib, sys

        sys.path.insert(0, "/root/repo")
        mod = importlib.import_module("__graft_entry__")
        mod.dryrun_multichip(8)
