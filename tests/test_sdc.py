"""Data-plane integrity (protocol v5) + silent-state-corruption (SDC)
attestation and rollback-powered self-healing.

Four layers, mirroring the subsystem's trust chain:

- **Wire**: every data-plane frame type (1-8) carries a crc32 trailer.
  The property suite proves a truncated, bit-flipped, or garbage-trailed
  datagram NEVER decodes as a data-plane message — it is dropped and
  counted (``data_crc_drops``), indistinguishable from loss, which
  rollback already absorbs. Stale-version (v4) frames are refused as
  version skew, never mis-counted as corruption and never desynced.
- **Memory**: ``integrity.attest_ring`` recomputes every occupied
  snapshot-ring row's two-lane digest against its save-time value, so a
  flipped bit in device memory is detected within one attestation
  interval — singleton runner and stacked ``[S, depth]`` serve rings
  alike (one vmapped pass).
- **Repair**: ``RollbackRunner.attest_and_repair`` /
  ``BatchedSessionCore.repair_slot`` restore the deepest digest-clean
  snapshot and resimulate from the as-used input log. The repair must
  land *bitwise* (equal to an uninterrupted serial replay), recompile
  nothing, and leave batch siblings untouched; an unrepairable ring
  raises a typed ``StateFault(reason="sdc")`` that the supervisor
  escalates to the donor-transfer rung (docs/serving.md#self-healing).
- **Disk**: a bit-flipped server checkpoint is refused by the
  digest-guarded loader as a typed ``ValueError`` and
  ``ServerCheckpointer.restore`` falls back to the next-newest clean
  file (counted in ``load_fallbacks``).
"""

import zlib

import numpy as np
import pytest

from bevy_ggrs_tpu import integrity
from bevy_ggrs_tpu.chaos import (
    ChaosPlan,
    ChaosSocket,
    CheckpointCorrupt,
    Corrupt,
    SnapshotCorrupt,
)
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.runner import RollbackRunner
from bevy_ggrs_tpu.session import EventKind, SessionState
from bevy_ggrs_tpu.session import protocol as proto
from bevy_ggrs_tpu.session.endpoint import (
    VERSION_MISMATCH_THRESHOLD,
    PeerEndpoint,
)
from bevy_ggrs_tpu.session.requests import (
    AdvanceFrame,
    LoadGameState,
    SaveGameState,
)
from bevy_ggrs_tpu.session.supervisor import Health
from bevy_ggrs_tpu.transport.loopback import LoopbackNetwork
from bevy_ggrs_tpu.utils import xla_cache
from tests.test_protocol_fuzz import _valid_messages
from tests.test_supervisor import (
    MAX_PRED,
    make_supervised,
    settled_checksums,
    sup_step,
)

DATA_PLANE_CLASSES = (
    proto.SyncRequest,
    proto.SyncReply,
    proto.InputMsg,
    proto.InputAck,
    proto.QualityReport,
    proto.QualityReply,
    proto.KeepAlive,
    proto.ChecksumReport,
)


# ---------------------------------------------------------------------------
# Wire: the v5 crc32 trailer property suite
# ---------------------------------------------------------------------------


def test_every_data_plane_frame_carries_crc_trailer():
    for msg in _valid_messages():
        wire = proto.encode(msg)
        assert wire[2] in proto.DATA_PLANE_TYPES
        (trailer,) = proto._CRC.unpack_from(wire, len(wire) - 4)
        assert trailer == (zlib.crc32(wire[:-4]) & 0xFFFFFFFF)
        assert proto.decode(wire) == msg  # the trailer round-trips


def test_control_plane_frames_not_enveloped():
    # Types 9+ carry their own per-chunk crc/digest; they get no trailer
    # and never count toward crc_mismatch.
    wire = proto.encode(proto.StateRequest(nonce=7, kind=proto.STATE_KIND_RING))
    assert wire[2] not in proto.DATA_PLANE_TYPES
    assert not proto.crc_mismatch(wire)
    assert proto.decode(wire) == proto.StateRequest(7, proto.STATE_KIND_RING)


def test_single_bit_flip_never_decodes_as_data_plane():
    """Exhaustive: EVERY single-bit flip of EVERY data-plane frame either
    fails to decode or (type-byte flips that land on an unenveloped
    control type) decodes as a non-data-plane message the session input
    path ignores. No flip ever injects a wrong input/ack/checksum."""
    for msg in _valid_messages():
        wire = proto.encode(msg)
        for bit in range(len(wire) * 8):
            flipped = bytearray(wire)
            flipped[bit // 8] ^= 1 << (bit % 8)
            got = proto.decode(bytes(flipped))
            assert not isinstance(got, DATA_PLANE_CLASSES), (
                msg, bit, got,
            )


def test_truncation_and_trailing_garbage_never_decode():
    for msg in _valid_messages():
        wire = proto.encode(msg)
        for cut in range(len(wire)):
            assert proto.decode(wire[:cut]) is None, (msg, cut)
        for garbage in (b"\x00", b"\xff" * 3, wire[-4:]):
            assert proto.decode(wire + garbage) is None, (msg, garbage)
            # ...and the drop is attributed to corruption, not version skew.
            assert proto.crc_mismatch(wire + garbage)


def test_crc_valid_but_stale_version_refused_as_skew():
    """A frame whose bytes are internally consistent but carry the v4
    version byte (the frozen-deploy peer) is refused by the version gate
    BEFORE the crc check: decode None, version_mismatch says 4, and
    crc_mismatch stays False so the drop is counted as skew — the typed
    refusal, never a desync and never a corruption stat."""
    for msg in _valid_messages():
        wire = proto.encode(msg)
        stale = bytes([wire[0], 4, wire[2]]) + wire[3:-4]  # v4: no trailer
        assert proto.decode(stale) is None
        assert proto.version_mismatch(stale) == 4
        assert not proto.crc_mismatch(stale)


def test_endpoint_drops_and_counts_corruption_separately_from_skew():
    ep = PeerEndpoint(("peer", 1), np.random.RandomState(0))
    wire = bytearray(proto.encode(proto.KeepAlive()))
    wire[-1] ^= 0x40  # break the trailer
    ep.note_undecodable(bytes(wire))
    assert ep.data_crc_drops == 1
    assert ep.version_mismatches == 0

    good = proto.encode(proto.SyncRequest(3))
    stale = bytes([good[0], 4, good[2]]) + good[3:-4]
    ep.note_undecodable(stale)
    assert ep.data_crc_drops == 1
    assert ep.version_mismatches == 1


def test_v4_peer_handshake_gets_typed_refusal():
    """A still-SYNCHRONIZING endpoint fed v4 datagrams emits one
    VERSION_MISMATCH event after the threshold — the session surfaces the
    skewed peer instead of stalling sync forever."""
    ep = PeerEndpoint(("peer", 1), np.random.RandomState(0))
    good = proto.encode(proto.SyncRequest(3))
    stale = bytes([good[0], 4, good[2]]) + good[3:-4]
    for _ in range(VERSION_MISMATCH_THRESHOLD):
        ep.note_undecodable(stale)
    kinds = [e.kind for e in ep.events]
    assert kinds.count(EventKind.VERSION_MISMATCH) == 1
    assert ep.version_mismatches == VERSION_MISMATCH_THRESHOLD


def test_p2p_pair_corrupt_window_drops_counted_zero_desyncs():
    """The P2P-pair acceptance drill: a real two-peer match under an
    aggressive Corrupt window converges bitwise with zero desyncs — every
    flipped datagram was dropped-and-counted at the receiving endpoint,
    then re-delivered by the redundant input spans."""
    net = LoopbackNetwork()
    plan = ChaosPlan(77, (Corrupt(0.3, 4.0, 0.10),))
    peers = [make_supervised(net, 2, me) for me in range(2)]
    for me, peer in enumerate(peers):
        peer[0].socket = ChaosSocket(
            peer[0].socket, plan, clock=lambda: net.now, addr=("peer", me)
        )
    for _ in range(330):
        net.advance(1.0 / 60.0)
        for peer in peers:
            sup_step(net, peer, lambda h, f: np.uint8((f // 3 + h) % 4))
    sessions = [p[0] for p in peers]
    for s, _, sup, m in peers:
        assert s.current_state() == SessionState.RUNNING
        assert sup.health in (Health.HEALTHY, Health.DEGRADED)
        assert m.counters.get("desyncs_detected", 0) == 0
    drops = sum(
        ep.data_crc_drops for s in sessions for ep in s._endpoints.values()
    )
    assert drops > 0
    assert sum(len(p[0].socket.faults) for p in peers) > 0
    frames, rows = settled_checksums(sessions)
    assert len(frames) >= 3
    for f, row in zip(frames, rows):
        assert row[0] == row[1], f"frame {f} diverged: {row}"


# ---------------------------------------------------------------------------
# Memory + repair: singleton runner
# ---------------------------------------------------------------------------

N_PLAYERS = 2


def mk_runner():
    r = RollbackRunner(
        box_game.make_schedule(),
        box_game.make_world(N_PLAYERS).commit(),
        max_prediction=MAX_PRED,
        num_players=N_PLAYERS,
        input_spec=box_game.INPUT_SPEC,
    )
    r.warmup()
    return r


def bits_for(f):
    z = box_game.INPUT_SPEC.zeros_np(N_PLAYERS)
    return np.stack(
        [box_game.INPUT_SPEC.zeros_np(1)[0] + ((f + h) % 3)
         for h in range(N_PLAYERS)]
    ).astype(z.dtype)


def advance(runner, frames, start=None):
    start = runner.frame if start is None else start
    for f in range(start, start + frames):
        runner.handle_requests(
            [SaveGameState(f),
             AdvanceFrame(bits_for(f), np.zeros(N_PLAYERS, np.int32))]
        )


def occupied_frames(ring):
    return sorted(int(f) for f in np.asarray(ring.frames).ravel() if f >= 0)


def test_clean_ring_attests_clean():
    runner = mk_runner()
    advance(runner, 24)
    assert not integrity.attest_ring(runner.ring).any()
    assert runner.attest_and_repair() == {
        "corrupt_frames": [], "repaired": 0, "repair_frames": 0,
        "bitwise": None, "first_corrupt_field": None,
    }
    assert runner.state_faults == []


def test_flip_detected_and_repaired_bitwise_no_recompile():
    runner, serial = mk_runner(), mk_runner()
    advance(runner, 30, start=0)
    advance(serial, 30, start=0)

    rng = np.random.RandomState(7)
    target = occupied_frames(runner.ring)[3]
    runner.ring, info = integrity.flip_ring_bit(
        runner.ring, target % runner.ring.depth, rng
    )
    assert integrity.attest_ring(runner.ring).any()

    xla_cache.install_compile_listeners()
    c0 = xla_cache.compile_counters()["backend_compiles"]
    report = runner.attest_and_repair()
    c1 = xla_cache.compile_counters()["backend_compiles"]

    assert report["corrupt_frames"] == [target]
    assert report["bitwise"] is True
    assert report["first_corrupt_field"] == info["field"]
    assert c1 - c0 == 0, "repair must reuse the warmed executable"
    assert not integrity.attest_ring(runner.ring).any()
    assert runner.sdc_detected_total == 1
    assert runner.sdc_repaired_total == 1
    assert [r["reason"] for r in runner.state_faults] == ["sdc"]
    assert runner.state_faults[0]["repaired"] is True

    # Bitwise witness: live state AND every ring row equal an
    # uninterrupted serial replay of the same inputs.
    import jax

    a = np.asarray(integrity._state_digest(runner.state))
    b = np.asarray(integrity._state_digest(serial.state))
    assert (a == b).all()
    for la, lb in zip(
        jax.tree_util.tree_leaves(runner.ring.states),
        jax.tree_util.tree_leaves(serial.ring.states),
    ):
        assert np.array_equal(np.asarray(la), np.asarray(lb))


def test_restore_path_verifies_rows_and_self_heals():
    """A rollback that targets a corrupt ring row must NOT silently
    resimulate from garbage: the restore-path guard attests, self-heals,
    and only then replays — the final state is bitwise what a clean run
    produces, with the incident on the typed fault log."""
    runner, serial = mk_runner(), mk_runner()
    advance(runner, 30)
    advance(serial, 30)

    rng = np.random.RandomState(9)
    back = occupied_frames(runner.ring)[4]
    runner.ring, _ = integrity.flip_ring_bit(
        runner.ring, back % runner.ring.depth, rng
    )
    top = runner.frame
    reqs = [LoadGameState(back)]
    for f in range(back, top):
        reqs += [SaveGameState(f),
                 AdvanceFrame(bits_for(f), np.zeros(N_PLAYERS, np.int32))]
    runner.handle_requests(reqs)

    assert not integrity.attest_ring(runner.ring).any()
    assert len(runner.state_faults) == 1
    assert runner.state_faults[0]["repaired"] is True
    assert runner.state_faults[0]["bitwise"] is True
    a = np.asarray(integrity._state_digest(runner.state))
    b = np.asarray(integrity._state_digest(serial.state))
    assert (a == b).all()


def test_unrepairable_ring_raises_typed_fault():
    runner = mk_runner()
    advance(runner, 12)
    rng = np.random.RandomState(5)
    for f in occupied_frames(runner.ring):
        runner.ring, _ = integrity.flip_ring_bit(
            runner.ring, f % runner.ring.depth, rng
        )
    with pytest.raises(integrity.StateFault) as exc:
        runner.attest_and_repair()
    assert exc.value.reason == "sdc"
    assert exc.value.frames  # names the corrupt frames
    rec = runner.state_faults[-1]
    assert rec["reason"] == "sdc" and rec["repaired"] is False


# ---------------------------------------------------------------------------
# Memory + repair: batched serve rings
# ---------------------------------------------------------------------------


def make_batch():
    from tests.test_batched_sessions import make_core, make_script

    core = make_core(num_slots=4)
    for _ in range(3):
        core.admit()
    scripts = {i: make_script(100 + i, depth=2 + (i % 2), cycles=6)
               for i in range(3)}
    n = min(len(v) for v in scripts.values())
    for t in range(n):
        core.tick({i: (scripts[i][t][0], scripts[i][t][1], None)
                   for i in range(3)})
    return core


def test_batched_attest_detects_exact_slots_and_repairs_bitwise():
    core = make_batch()
    assert core.attest() == {}

    pre = np.asarray(integrity._states_digests(core.states)).copy()
    rng = np.random.RandomState(11)
    frames_h = np.asarray(core.rings.frames)
    injected = {}
    for slot, nrows in ((1, 2), (2, 1)):
        occ = sorted(int(f) for f in frames_h[slot] if f >= 0)
        for tf in occ[1:1 + nrows]:
            core.rings, _ = integrity.flip_ring_bit(
                core.rings, tf % core.ring_depth, rng, slot=slot
            )
            injected.setdefault(slot, []).append(tf)

    detected = core.attest()
    assert detected == injected  # exact slots, exact frames

    xla_cache.install_compile_listeners()
    c0 = xla_cache.compile_counters()["backend_compiles"]
    for slot, bad in detected.items():
        rep = core.repair_slot(slot, bad)
        assert rep["bitwise"] is True
        assert rep["repaired"] == len(bad)
    c1 = xla_cache.compile_counters()["backend_compiles"]
    assert c1 - c0 == 0

    assert core.attest() == {}
    post = np.asarray(integrity._states_digests(core.states))
    # Repaired slots land bitwise AND siblings were never touched.
    assert (pre == post).all()


def test_batched_unrepairable_slot_faults_with_slot_index():
    core = make_batch()
    rng = np.random.RandomState(13)
    frames_h = np.asarray(core.rings.frames)[0]
    for f in (int(x) for x in frames_h if x >= 0):
        core.rings, _ = integrity.flip_ring_bit(
            core.rings, f % core.ring_depth, rng, slot=0
        )
    detected = core.attest()
    with pytest.raises(integrity.StateFault) as exc:
        core.repair_slot(0, detected[0])
    assert exc.value.reason == "sdc"
    assert exc.value.slot == 0


# ---------------------------------------------------------------------------
# Disk: checkpoint corruption -> typed refusal -> newest-clean fallback
# ---------------------------------------------------------------------------


def test_corrupt_checkpoint_refused_and_restore_falls_back(tmp_path):
    from tests.test_serve_faults import inputs_for, make_server, make_synctest
    from bevy_ggrs_tpu.serve.faults import load_checkpoint_matches

    ckpt = str(tmp_path / "ckpts")
    server = make_server(checkpoint_dir=ckpt, checkpoint_interval=6,
                         checkpoint_keep=3)
    handles = [server.add_match(make_synctest(), inputs_for(k))
               for k in (11, 12)]
    for _ in range(12):
        server.run_frame()
    assert server.checkpointer.saves_total == 2
    newest = server.checkpointer.latest()
    del server

    info = integrity.flip_file_bit(newest, np.random.RandomState(3))
    assert info is not None

    # The guarded loader refuses the flipped file as a typed ValueError
    # (never an unpickling crash, never a plausible impostor state).
    revived = make_server(checkpoint_dir=ckpt, checkpoint_interval=6,
                          checkpoint_keep=3)
    with pytest.raises(ValueError, match="corrupt server checkpoint"):
        load_checkpoint_matches(newest, revived.state_codec())

    # restore() with no explicit path skips it and restores every match
    # from the next-newest clean checkpoint (frame 6, not 12).
    attachments = {
        (h.group, h.slot): {"session": make_synctest(),
                            "local_inputs": inputs_for(k)}
        for h, k in zip(handles, (11, 12))
    }
    restored = revived.checkpointer.restore(revived, attachments)
    assert {(h.group, h.slot) for h in restored} == set(attachments)
    assert revived.checkpointer.load_fallbacks == 1
    for h in handles:
        assert revived.groups[h.group].slots[h.slot].frame == 6

    # An explicitly named corrupt path NEVER falls back silently.
    with pytest.raises(ValueError, match="corrupt server checkpoint"):
        make_server(checkpoint_dir=ckpt).checkpointer.restore(
            make_server(checkpoint_dir=ckpt), attachments, path=newest
        )


# ---------------------------------------------------------------------------
# Supervisor: periodic attestation, typed events, donor escalation
# ---------------------------------------------------------------------------


def drive_pair(net, peers, n, events=None):
    for _ in range(n):
        net.advance(1.0 / 60.0)
        for peer in peers:
            sup_step(
                net, peer, lambda h, f: np.uint8((f // 3 + h) % 4),
                events=events,
            )


def test_supervisor_attests_heals_in_place_and_emits_typed_event():
    net = LoopbackNetwork()
    peers = [make_supervised(net, 2, me) for me in range(2)]
    for _, _, sup, _ in peers:
        sup.attest_interval = 4  # tight cadence for the drill
    drive_pair(net, peers, 60)
    session, runner, sup, metrics = peers[0]
    assert session.current_state() == SessionState.RUNNING

    rng = np.random.RandomState(21)
    occ = occupied_frames(runner.ring)
    target = occ[len(occ) // 2]
    runner.ring, _ = integrity.flip_ring_bit(
        runner.ring, target % runner.ring.depth, rng
    )

    events = []
    drive_pair(net, peers, 90, events=events)

    sdc = [e for e in events if e.kind == EventKind.STATE_FAULT]
    assert len(sdc) >= 1
    assert sdc[0].data["reason"] == "sdc"
    assert sdc[0].data["repaired"] is True
    assert sdc[0].data["bitwise"] is True
    assert metrics.counters["sdc_faults"] >= 1
    # Quarantine-free: the repair landed bitwise, so the timeline provably
    # never diverged — no desync, no health excursion, checksums agree.
    assert sup.health is Health.HEALTHY
    assert metrics.counters.get("quarantines", 0) == 0
    assert metrics.counters.get("desyncs_detected", 0) == 0
    frames, rows = settled_checksums([peers[0][0], peers[1][0]])
    assert frames and all(r[0] == r[1] for r in rows)


def test_supervisor_escalates_unrepairable_to_donor_transfer():
    net = LoopbackNetwork()
    peers = [make_supervised(net, 2, me) for me in range(2)]
    for _, _, sup, _ in peers:
        sup.attest_interval = 4
    drive_pair(net, peers, 60)
    session, runner, sup, metrics = peers[0]

    rng = np.random.RandomState(23)
    for f in occupied_frames(runner.ring):
        runner.ring, _ = integrity.flip_ring_bit(
            runner.ring, f % runner.ring.depth, rng
        )

    events = []
    drive_pair(net, peers, 240, events=events)

    # Rung 2 of the ladder: local repair impossible -> quarantine ->
    # digest-verified donor snapshot -> replay forward -> healthy again.
    assert metrics.counters["sdc_escalations"] >= 1
    assert metrics.counters["recoveries"] >= 1
    sdc = [e for e in events if e.kind == EventKind.STATE_FAULT]
    assert any(e.data["repaired"] is False for e in sdc)
    assert sup.health in (Health.HEALTHY, Health.DEGRADED)
    assert session.current_state() == SessionState.RUNNING
    frames, rows = settled_checksums([peers[0][0], peers[1][0]])
    assert frames and all(r[0] == r[1] for r in rows)


# ---------------------------------------------------------------------------
# ChaosPlan: the StateFault directive family
# ---------------------------------------------------------------------------


def test_sdc_family_drawn_last_keeps_old_plans_byte_identical():
    peers = (("peer", 0), ("peer", 1))
    base = ChaosPlan.generate(
        31, 30.0, peers, kill_restart=True, match_server=("srv", 0)
    )
    with_sdc = ChaosPlan.generate(
        31, 30.0, peers, kill_restart=True, match_server=("srv", 0), sdc=True
    )
    # Every pre-existing draw is untouched; the sdc family is appended.
    assert with_sdc.directives[: len(base.directives)] == base.directives
    snaps = with_sdc.snapshot_corrupts()
    assert len(snaps) == 2
    assert all(0.2 * 30.0 <= d.at <= 0.7 * 30.0 for d in snaps)
    assert all(d.target in peers for d in snaps)
    ckcs = with_sdc.checkpoint_corrupts()
    assert len(ckcs) == 1 and ckcs[0].target == ("srv", 0)
    assert 0.6 * 30.0 <= ckcs[0].at <= 0.85 * 30.0
    # Seed-replayable like every other family.
    assert with_sdc == ChaosPlan.generate(
        31, 30.0, peers, kill_restart=True, match_server=("srv", 0), sdc=True
    )


def test_sdc_directives_json_roundtrip_and_horizon():
    plan = ChaosPlan(
        5,
        (
            Corrupt(1.0, 2.0, 0.05),
            SnapshotCorrupt(3.0, ("peer", 1)),
            CheckpointCorrupt(4.5, "server"),
        ),
    )
    back = ChaosPlan.from_json(plan.to_json())
    assert back == plan
    assert back.snapshot_corrupts()[0].target == ("peer", 1)
    assert back.checkpoint_corrupts()[0].target == "server"
    assert plan.horizon() >= 4.5
