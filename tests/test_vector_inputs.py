"""Structured speculation trees for NON-SCALAR inputs (round-2 weak #4).

A twin-stick-style test model carries a vector input per player —
``[move_bitmask, throttle_level]`` as ``uint8[2]`` — exercising the
generalized single-change tree: each branch changes one player's one FIELD
to one candidate value at one frame and holds, so a throttle-change
misprediction is recoverable as a branch commit exactly like a scalar
bitmask press. The sticky random sampler's measured hit rate on such
changes was 0 (ROUND_NOTES r1); these tests pin the structured tree's to
hits."""

import jax.numpy as jnp
import numpy as np

from bevy_ggrs_tpu.runner import RollbackRunner
from bevy_ggrs_tpu.schedule import InputSpec, PlayerInputs, Schedule
from bevy_ggrs_tpu.session.requests import AdvanceFrame, LoadGameState, SaveGameState
from bevy_ggrs_tpu.spec_runner import (
    SpeculativeRollbackRunner,
    attest_speculation_safety,
)
from bevy_ggrs_tpu.state import HostWorld, TypeRegistry

INPUT_UP, INPUT_DOWN, INPUT_LEFT, INPUT_RIGHT = 1, 2, 4, 8
# Field 0: movement bitmask 0..15; field 1: throttle 0..15.
INPUT_SPEC = InputSpec(shape=(2,), dtype=jnp.uint8, values=tuple(range(16)))
P = 2


def make_registry():
    reg = TypeRegistry()
    reg.register_component("position", shape=(2,), dtype=jnp.float32)
    reg.register_component("owner", shape=(), dtype=jnp.int32, default=-1)
    reg.register_resource("frame_count", jnp.uint32(0))
    return reg


def make_world():
    world = HostWorld(make_registry(), 4)
    for h in range(P):
        world.spawn(
            {"position": np.array([float(h), 0.0], np.float32), "owner": h},
            rollback_id=h,
        )
    return world


def move_system(state, inputs: PlayerInputs):
    """Integer-graded movement: direction from field 0's bitmask, speed
    scaled by field 1's throttle level. f32 add/mul with fixed order —
    bit-reproducible, so speculation attests safe."""
    owner = state.components["owner"]
    pos = state.components["position"]
    safe = jnp.clip(owner, 0, inputs.num_players - 1)
    bits = inputs.bits[safe, 0].astype(jnp.uint32)
    throttle = inputs.bits[safe, 1].astype(jnp.float32)
    dx = (
        ((bits & INPUT_RIGHT) != 0).astype(jnp.float32)
        - ((bits & INPUT_LEFT) != 0).astype(jnp.float32)
    )
    dy = (
        ((bits & INPUT_UP) != 0).astype(jnp.float32)
        - ((bits & INPUT_DOWN) != 0).astype(jnp.float32)
    )
    step = jnp.stack([dx, dy], axis=1) * (
        jnp.float32(0.01) * (jnp.float32(1.0) + throttle)[:, None]
    )
    sel = (state.alive & (owner >= 0))[:, None]
    return state.replace(
        components={
            **state.components,
            "position": jnp.where(sel, pos + step, pos),
        }
    )


def frame_system(state, inputs):
    del inputs
    return state.replace(
        resources={
            **state.resources,
            "frame_count": state.resources["frame_count"] + jnp.uint32(1),
        }
    )


def make_schedule():
    return Schedule([move_system, frame_system])


def adv(vec):
    return AdvanceFrame(
        bits=np.asarray(vec, np.uint8), status=np.zeros(P, np.int32)
    )


def step_requests(frame, vec):
    return [SaveGameState(frame), adv(vec)]


def rollback_requests(load, corrected):
    reqs = [LoadGameState(load)]
    for t, vec in enumerate(corrected):
        reqs += [SaveGameState(load + t), adv(vec)]
    return reqs


class Log:
    def __init__(self):
        self.seen = {}

    def report_checksum(self, frame, cs):
        self.seen[frame] = int(cs)


def make_runners(num_branches=128, spec_frames=4):
    serial = RollbackRunner(
        make_schedule(), make_world().commit(),
        max_prediction=8, num_players=P, input_spec=INPUT_SPEC,
    )
    spec = SpeculativeRollbackRunner(
        make_schedule(), make_world().commit(),
        max_prediction=8, num_players=P, input_spec=INPUT_SPEC,
        num_branches=num_branches, spec_frames=spec_frames,
    )
    return serial, spec


def test_vector_model_attests_safe():
    _, spec = make_runners(num_branches=8)
    assert attest_speculation_safety(spec).ok


def test_single_field_change_is_a_spec_hit():
    """Player 1 changes ONLY the throttle field (field 1) at the anchor;
    the structured tree enumerates that single-field change, so the
    rollback burst commits a precomputed branch."""
    serial, spec = make_runners()
    logs = (Log(), Log())
    base = np.zeros((P, 2), np.uint8)
    base[:, 0] = INPUT_RIGHT  # both players holding right, throttle 0
    for f in range(3):
        serial.handle_requests(step_requests(f, base), logs[0])
        spec.handle_requests(step_requests(f, base), logs[1])
    spec.speculate(2)  # anchor 3
    for f in (3, 4):
        serial.handle_requests(step_requests(f, base), logs[0])
        spec.handle_requests(step_requests(f, base), logs[1])
    # Truth: player 1 pushed throttle to 5 at frame 3 and held.
    changed = base.copy()
    changed[1, 1] = 5
    corrected = [changed, changed]
    serial.handle_requests(rollback_requests(3, corrected), logs[0])
    spec.handle_requests(rollback_requests(3, corrected), logs[1])

    assert spec.spec_hits == 1 and spec.spec_misses == 0
    assert serial.frame == spec.frame
    assert logs[0].seen == logs[1].seen  # bitwise agreement with serial


def test_two_field_change_falls_back_serial():
    """A simultaneous two-field change is outside the single-change tree:
    must be a MISS that falls back to (bit-identical) serial replay — the
    correctness contract is unconditional, only the hit rate varies."""
    serial, spec = make_runners()
    logs = (Log(), Log())
    base = np.zeros((P, 2), np.uint8)
    for f in range(3):
        serial.handle_requests(step_requests(f, base), logs[0])
        spec.handle_requests(step_requests(f, base), logs[1])
    spec.speculate(2)
    for f in (3, 4):
        serial.handle_requests(step_requests(f, base), logs[0])
        spec.handle_requests(step_requests(f, base), logs[1])
    changed = base.copy()
    changed[1] = [INPUT_UP, 7]  # move AND throttle changed together
    corrected = [changed, changed]
    serial.handle_requests(rollback_requests(3, corrected), logs[0])
    spec.handle_requests(rollback_requests(3, corrected), logs[1])

    assert spec.spec_hits == 0 and spec.spec_misses == 1
    assert serial.frame == spec.frame
    assert logs[0].seen == logs[1].seen


def test_structured_tree_enumerates_fields_scalar_compatible():
    """Direct tree inspection: every non-base branch differs from base in
    exactly one (player, field) suffix; scalar models keep their old tree
    shape (ndindex(()) degenerates to one field)."""
    _, spec = make_runners(num_branches=64, spec_frames=3)
    last = np.zeros((P, 2), np.uint8)
    known = np.zeros((3, P, 2), np.uint8)
    mask = np.zeros((3, P), bool)
    tree = spec._structured_bits(last, known, mask)
    assert tree.shape == (64, 3, P, 2)
    base = tree[0]
    for b in range(1, 64):
        diff = tree[b] != base
        changed = np.argwhere(diff)
        assert len(changed) > 0
        # All diffs share one (player, field) and form a frame suffix.
        players = {(p, f) for _, p, f in changed}
        assert len(players) == 1
        frames = sorted({t for t, _, _ in changed})
        assert frames == list(range(frames[0], 3))


def test_vector_speculation_live_session_equivalence_and_hits():
    """Full two-peer loopback P2P with the twin-stick vector model: the
    speculating peer's confirmed checksum stream must equal the all-serial
    universe's, and the structured single-field tree must land real hits
    against scripted single-field input changes."""
    from bevy_ggrs_tpu.session import (
        PlayerType,
        PredictionThreshold,
        SessionBuilder,
        SessionState,
    )
    from bevy_ggrs_tpu.transport.loopback import LoopbackNetwork

    FPS_DT = 1.0 / 60.0

    def scripted_vec(handle, frame):
        """One FIELD changes at a time, every 4 frames: move bitmask cycles
        on even periods, throttle steps on odd — the misprediction shape
        the single-change tree enumerates."""
        vec = np.zeros(2, np.uint8)
        period = frame // 4
        vec[0] = [INPUT_UP, INPUT_RIGHT, 0, INPUT_DOWN][(period // 2 + handle) % 4]
        # Throttle steps only on odd periods (held through even ones), so
        # each period boundary changes at most one field.
        vec[1] = (period + period % 2 + handle) % 4
        return vec

    def drive(speculate):
        net = LoopbackNetwork(latency=2.5 * FPS_DT, seed=31)
        peers = []
        for me in range(P):
            sock = net.socket(("peer", me))
            builder = (
                SessionBuilder(INPUT_SPEC)
                .with_num_players(P)
                .with_max_prediction_window(8)
            )
            for h in range(P):
                builder.add_player(
                    PlayerType.local() if h == me
                    else PlayerType.remote(("peer", h)),
                    h,
                )
            session = builder.start_p2p_session(sock, clock=lambda: net.now)
            if me == 0 and speculate:
                runner = SpeculativeRollbackRunner(
                    make_schedule(), make_world().commit(),
                    max_prediction=8, num_players=P, input_spec=INPUT_SPEC,
                    num_branches=128, spec_frames=8, seed=5,
                )
            else:
                runner = RollbackRunner(
                    make_schedule(), make_world().commit(),
                    max_prediction=8, num_players=P, input_spec=INPUT_SPEC,
                )
            peers.append((session, runner))
        for _ in range(70):
            net.advance(FPS_DT)
            for session, runner in peers:
                session.poll_remote_clients()
                if session.current_state() != SessionState.RUNNING:
                    continue
                for h in session.local_player_handles():
                    session.add_local_input(
                        h, scripted_vec(h, session.current_frame)
                    )
                try:
                    requests = session.advance_frame()
                except PredictionThreshold:
                    continue
                runner.handle_requests(requests, session)
                if isinstance(runner, SpeculativeRollbackRunner):
                    runner.speculate(session.confirmed_frame(), session)
        return peers

    spec_peers = drive(True)
    serial_peers = drive(False)

    from tests.test_p2p import common_confirmed_checksums

    f1, cs1 = common_confirmed_checksums(spec_peers)
    f2, cs2 = common_confirmed_checksums(serial_peers)
    assert f1 and f1 == f2
    assert all(a == b for a, b in cs1)
    assert cs1 == cs2  # speculation invisible in the vector universe too
    spec_runner = spec_peers[0][1]
    assert spec_runner.rollbacks_total > 0
    # The structured single-field tree recovers real mispredictions live.
    assert spec_runner.spec_hits + spec_runner.spec_partial_hits > 0


def test_periodic_extrapolation_per_field_vector_inputs():
    """Per-(player, FIELD) period detection: field 0 cycles with period 4,
    field 1 holds constant — the extrapolated base must continue field 0's
    cycle exactly while leaving field 1 on repeat-last, independently per
    player (players offset in phase)."""
    spec = SpeculativeRollbackRunner(
        make_schedule(), make_world().commit(),
        max_prediction=8, num_players=P, input_spec=INPUT_SPEC,
        num_branches=16, spec_frames=8,
    )
    cycle = [1, 2, 4, 8]

    def field0(h, f):
        return cycle[(f + h) % 4]

    for f in range(40):
        spec._input_log[f] = np.array(
            [[field0(h, f), 7] for h in range(P)], np.uint8
        )
    anchor = 40
    last = spec._input_log[anchor - 1]
    known = np.zeros((8, P, 2), np.uint8)
    mask = np.zeros((8, P), bool)
    tree = spec._structured_bits(last, known, mask, anchor)
    truth = np.array(
        [[[field0(h, anchor + t), 7] for h in range(P)] for t in range(8)],
        np.uint8,
    )
    # Branch 0 = forward-fill (field 0 stuck on its last value).
    assert np.array_equal(tree[0], np.broadcast_to(last, (8, P, 2)))
    assert not np.array_equal(tree[0], truth)
    # Branch 1 = the true per-field periodic continuation.
    assert np.array_equal(tree[1], truth), (tree[1], truth)
