"""Online time-series pipeline contracts (obs/timeseries.py):

- The P² streaming quantile sketch tracks numpy's exact percentiles
  within a few percent on common latency shapes, is EXACT below five
  samples, and costs O(1) memory per (series, quantile).
- :class:`MetricWindow` keeps an exact bounded ring alongside the
  sketches: ``window_percentile`` over the ring matches numpy on the
  tail, and the ring never exceeds its bound.
- :class:`TimeSeries` enforces a series-cardinality ceiling (drops and
  counts, never grows unbounded), and ``null_timeseries`` keeps
  telemetry-off call sites unconditional and free.
- Export surfaces: Prometheus summaries + window gauges, HTML-report
  section, and the server's front-door SLO JSON artifact.
- The overhead acceptance: feeding the pipeline from the hot serving
  path adds at most 5% of the 60 Hz frame budget per batched tick at
  S=256 (the ISSUE's test-enforced ceiling).
"""

import json

import numpy as np
import pytest

from bevy_ggrs_tpu.obs import (
    MetricWindow,
    P2Quantile,
    TimeSeries,
    WindowSLO,
    null_timeseries,
)
from bevy_ggrs_tpu.obs.prom import export_prometheus
from bevy_ggrs_tpu.obs.report import build_report
from bevy_ggrs_tpu.obs.slo import LEVEL_OK, LEVEL_PAGE, SLOConfig
from bevy_ggrs_tpu.utils.metrics import Metrics
from tests.test_batched_sessions import drive, make_core, make_script


# ---------------------------------------------------------------------------
# P² sketch accuracy
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("q", [0.5, 0.95, 0.99])
@pytest.mark.parametrize(
    "draw",
    [
        lambda rng, n: rng.normal(10.0, 2.0, n),
        lambda rng, n: rng.exponential(4.0, n) + 1.0,
        lambda rng, n: rng.uniform(2.0, 20.0, n),
    ],
    ids=["normal", "exponential", "uniform"],
)
def test_p2_tracks_numpy_percentiles(q, draw):
    rng = np.random.RandomState(17)
    xs = draw(rng, 8000)
    sk = P2Quantile(q)
    for x in xs:
        sk.add(float(x))
    true = float(np.percentile(xs, q * 100.0))
    # P2's five markers track central quantiles tightly; the extreme
    # tail of a heavy-tailed stream is its documented weak spot, so the
    # envelope widens at p99 (exact tail reads use window_percentile).
    tol = 0.08 if q >= 0.99 else 0.05
    assert abs(sk.value() - true) <= tol * abs(true), (
        f"P2(q={q}) = {sk.value():.4f} vs numpy {true:.4f}"
    )


def test_p2_exact_below_five_samples():
    sk = P2Quantile(0.5)
    for i, x in enumerate([5.0, 1.0, 3.0]):
        sk.add(x)
    assert sk.value() == 3.0  # exact median of {1,3,5}
    sk2 = P2Quantile(0.99)
    sk2.add(7.0)
    assert sk2.value() == 7.0


def test_p2_constant_stream_is_exact():
    sk = P2Quantile(0.95)
    for _ in range(100):
        sk.add(4.25)
    assert sk.value() == 4.25


# Adversarial streams: the two shapes a streaming sketch classically
# flubs — fully sorted input (every sample lands past the last marker)
# and a constant plateau broken by a step (degenerate markers, then a
# regime change). The envelope invariant (estimate within the stream's
# observed [min, max]) must hold unconditionally; accuracy claims are
# pinned only where P² actually delivers them.


@pytest.mark.parametrize("q", [0.5, 0.95, 0.99])
@pytest.mark.parametrize("ascending", [True, False],
                         ids=["ascending", "descending"])
def test_p2_sorted_stream_stays_tight(q, ascending):
    xs = [float(i) for i in range(1, 5001)]
    if not ascending:
        xs.reverse()
    sk = P2Quantile(q)
    for x in xs:
        sk.add(x)
    true = float(np.percentile(xs, q * 100.0))
    # Monotone input is P2's best case — markers glide with the stream.
    assert abs(sk.value() - true) <= 0.001 * true, (
        f"sorted stream: P2(q={q}) = {sk.value():.2f} vs numpy {true:.2f}"
    )
    assert xs[0] <= sk.value() <= xs[-1] or xs[-1] <= sk.value() <= xs[0]


def test_p2_constant_then_step_high_quantiles_follow():
    # 1000 samples at 1.0 (markers fully degenerate), then 1000 at
    # 100.0: the true p95/p99 jump to the step value and the sketch
    # must follow it there — a sketch stuck on the plateau would hide
    # a 100x latency regression from every SLO built on it.
    for q in (0.95, 0.99):
        sk = P2Quantile(q)
        for _ in range(1000):
            sk.add(1.0)
        assert sk.value() == 1.0  # exact while the stream is constant
        for _ in range(1000):
            sk.add(100.0)
        assert abs(sk.value() - 100.0) <= 1e-6, (
            f"P2(q={q}) = {sk.value():.4f} never reached the step"
        )


def test_p2_constant_then_step_median_is_bounded_not_exact():
    # The documented weak spot: the median marker interpolates across
    # the 1.0 -> 100.0 cliff, so p50 smears (true 50.5, estimate lands
    # well below). Pin the CONTRACT, not the flaw's exact value: the
    # estimate stays inside the observed envelope, and exact tail reads
    # belong to MetricWindow.window_percentile (next test).
    sk = P2Quantile(0.5)
    xs = [1.0] * 1000 + [100.0] * 1000
    for x in xs:
        sk.add(x)
    assert 1.0 <= sk.value() <= 100.0
    w = MetricWindow("frame_ms", window=2000)
    for x in xs:
        w.observe(x)
    assert w.window_percentile(0.5) == float(np.percentile(xs, 50.0))


# ---------------------------------------------------------------------------
# MetricWindow: sketches + exact ring
# ---------------------------------------------------------------------------


def test_window_ring_is_bounded_and_exact():
    w = MetricWindow("frame_ms", window=32)
    for i in range(100):
        w.observe(float(i))
    vals = w.window_values()
    assert vals == [float(i) for i in range(68, 100)]  # last 32, in order
    assert w.window_percentile(0.5) == pytest.approx(
        float(np.percentile(vals, 50.0))
    )
    snap = w.snapshot()
    assert snap["count"] == 100 and snap["window_n"] == 32
    assert {"p50", "p95", "p99", "window_p50", "window_p99"} <= set(snap)


def test_window_untracked_quantile_raises():
    w = MetricWindow("x", window=8, quantiles=(0.5,))
    w.observe(1.0)
    with pytest.raises(KeyError):
        w.percentile(0.99)


# ---------------------------------------------------------------------------
# TimeSeries: registry + cardinality ceiling + null object
# ---------------------------------------------------------------------------


def test_timeseries_cardinality_guard_drops_and_counts():
    ts = TimeSeries(window=8, max_series=3)
    for k in range(5):
        ts.observe(f"series_{k}", 1.0)
    assert len(ts.names()) == 3
    assert ts.dropped == 2
    assert ts.window_for("series_4") is None
    snap = ts.snapshot()
    assert set(snap) == {"series_0", "series_1", "series_2"}


def test_null_timeseries_is_free_and_unconditional():
    null_timeseries.observe("anything", 1.0)
    assert null_timeseries.enabled is False
    assert null_timeseries.names() == []
    assert null_timeseries.window_for("anything") is None
    assert null_timeseries.snapshot() == {}


# ---------------------------------------------------------------------------
# Export surfaces
# ---------------------------------------------------------------------------


def test_prometheus_export_emits_summaries_and_window_gauges():
    ts = TimeSeries(window=16)
    for i in range(50):
        ts.observe("admission_ms", float(i % 10) + 1.0)
    text = export_prometheus(Metrics(), timeseries=ts)
    assert "# TYPE ggrs_ts_admission_ms summary" in text
    assert 'ggrs_ts_admission_ms{quantile="0.5"}' in text
    assert 'ggrs_ts_admission_ms{quantile="0.99"}' in text
    assert "ggrs_ts_admission_ms_count 50" in text
    assert 'ggrs_ts_admission_ms_window{quantile="0.99"}' in text


def test_report_renders_timeseries_section():
    ts = TimeSeries(window=16)
    for i in range(20):
        ts.observe("frame_ms", 16.0 + i * 0.01)
    html = build_report(metrics=Metrics(), timeseries=ts)
    assert "Time series (live windows)" in html
    assert "frame_ms" in html


# ---------------------------------------------------------------------------
# WindowSLO: objectives over live windows
# ---------------------------------------------------------------------------


def make_window_slo(threshold=8.0, objective=0.99):
    ts = TimeSeries(window=128)
    slo = WindowSLO(
        ts,
        {"admission": ("admission_ms", threshold, objective)},
        config=SLOConfig(),
        metrics=Metrics(),
    )
    return ts, slo


def test_window_slo_all_good_is_ok_and_all_bad_pages():
    ts, slo = make_window_slo()
    for _ in range(64):
        ts.observe("admission_ms", 2.0)
    assert slo.level("admission") == LEVEL_OK
    for _ in range(128):
        ts.observe("admission_ms", 50.0)
    assert slo.level("admission") == LEVEL_PAGE
    levels = slo.export()
    assert levels["admission"] == LEVEL_PAGE
    assert slo.metrics.counters[
        'slo_level_transitions{objective="admission",to="page"}'
    ] == 1


def test_window_slo_cold_start_never_alerts():
    ts, slo = make_window_slo()
    for _ in range(8):  # below min_samples
        ts.observe("admission_ms", 999.0)
    assert slo.level("admission") == LEVEL_OK


# ---------------------------------------------------------------------------
# Overhead acceptance: <= 5% of frame budget at S=256
# ---------------------------------------------------------------------------


def test_observe_is_cheap_micro():
    """Fast guardrail: one observe (ring append + three P2 updates)
    stays far under the per-slot budget even with a 25x safety margin."""
    import time

    ts = TimeSeries(window=512)
    n = 20000
    t0 = time.perf_counter()
    for i in range(n):
        ts.observe("lat", float(i & 1023))
    per = (time.perf_counter() - t0) / n
    assert per < 50e-6, f"observe costs {per * 1e6:.2f} us"


@pytest.mark.slow
class TestTimeseriesOverhead:
    def test_timeseries_path_overhead_within_5pct_of_frame_budget_s256(
        self,
    ):
        """Acceptance (ISSUE 11): the online time-series pipeline fed
        from the hot dispatch path (host-work decomposition + sketch
        updates) adds at most 5% of the 60 Hz frame budget per batched
        tick at S=256."""
        import time

        S, frame_ms = 256, 1000.0 / 60.0

        def timed(timeseries):
            kw = {}
            if timeseries:
                kw = dict(timeseries=TimeSeries())
            core = make_core(num_slots=S, **kw)
            slots = [core.admit() for _ in range(S)]
            scripts = {
                s: make_script(seed=900 + s, depth=1 + (s % 4), cycles=3)
                for s in slots
            }
            ticks = max(len(v) for v in scripts.values())
            t0 = time.perf_counter()
            drive(core, scripts)
            return (time.perf_counter() - t0) * 1000.0 / ticks

        base = timed(False)
        timed(True)  # warm both paths' executables first
        enabled = timed(True)
        overhead = enabled - base
        assert overhead <= 0.05 * frame_ms, (
            f"timeseries path adds {overhead:.3f} ms/tick at S={S} "
            f"(budget {0.05 * frame_ms:.3f} ms; base {base:.3f} ms, "
            f"enabled {enabled:.3f} ms)"
        )


# ---------------------------------------------------------------------------
# Host-work decomposition (serve/batch.py)
# ---------------------------------------------------------------------------


def test_dispatch_decomposes_branch_build_and_arg_assembly():
    ts = TimeSeries()
    core = make_core(num_slots=4, timeseries=ts)
    slots = [core.admit() for _ in range(4)]
    scripts = {
        s: make_script(seed=40 + s, depth=2, cycles=2) for s in slots
    }
    drive(core, scripts)
    assert {"serve_branch_build_ms", "serve_arg_assembly_ms"} <= set(
        ts.names()
    )
    assert core.last_branch_build_ms >= 0.0
    assert core.last_arg_assembly_ms >= 0.0
    assert ts.window_for("serve_branch_build_ms").count > 0


def test_decomposition_off_when_telemetry_off():
    core = make_core(num_slots=2)
    assert core._measure_host is False
    s = core.admit()
    drive(core, {s: make_script(seed=1, depth=1, cycles=1)})
    assert core.last_branch_build_ms == 0.0
    assert core.last_arg_assembly_ms == 0.0


def test_front_door_slo_json_artifact(tmp_path):
    """export_telemetry writes the WindowSLO snapshot when the live
    pipeline is enabled."""
    from tests.test_serve_faults import inputs_for, make_server, make_synctest

    srv = make_server(
        metrics=Metrics(), timeseries=TimeSeries(), capacity=2
    )
    srv.add_match(make_synctest(), inputs_for(3))
    for _ in range(20):
        srv.run_frame()
    out = srv.export_telemetry(str(tmp_path), prefix="t")
    slo_path = tmp_path / "t_front_door_slo.json"
    assert slo_path.exists()
    snap = json.loads(slo_path.read_text())
    assert "admission" in snap["objectives"]
    assert "frame_deadline" in snap["objectives"]
    prom = (
        tmp_path / "t_metrics.prom"
        if (tmp_path / "t_metrics.prom").exists()
        else None
    )
    # frame_ms flows into the live pipeline every served frame.
    assert srv.timeseries.window_for("frame_ms").count >= 20
