"""SpeculativeRollbackRunner: recovery-as-select must be invisible.

Two layers: request-level unit tests crafting exact rollback bursts against
a hand-built branch tensor (hit, miss, partial-span, anchor-offset cases),
asserting bitwise equality with the serial runner and correct hit/miss
accounting; and a full two-peer loopback session where one peer speculates
— confirmed checksum streams must match the all-serial universe exactly.
"""

import jax.numpy as jnp
import numpy as np

from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.runner import RollbackRunner
from bevy_ggrs_tpu.session.requests import AdvanceFrame, LoadGameState, SaveGameState
from bevy_ggrs_tpu.spec_runner import SpeculativeRollbackRunner
from bevy_ggrs_tpu.state import combine64, checksum

P = 2
MAXPRED = 8


def fixed_sampler(tensor):
    """A sampler that always returns ``tensor`` ([B, F, P] uint8)."""
    t = jnp.asarray(tensor)

    def sample(key, last_bits, num_branches, num_frames):
        assert t.shape[0] == num_branches and t.shape[1] == num_frames
        return t
    return sample


def make_runners(sampler=None, num_branches=4, spec_frames=4, **kw):
    serial = RollbackRunner(
        box_game.make_schedule(), box_game.make_world(P).commit(),
        max_prediction=MAXPRED, num_players=P, input_spec=box_game.INPUT_SPEC,
    )
    spec = SpeculativeRollbackRunner(
        box_game.make_schedule(), box_game.make_world(P).commit(),
        max_prediction=MAXPRED, num_players=P, input_spec=box_game.INPUT_SPEC,
        num_branches=num_branches, sampler=sampler, spec_frames=spec_frames,
        **kw,
    )
    return serial, spec


def adv(bits):
    return AdvanceFrame(
        bits=np.asarray(bits, np.uint8), status=np.zeros(P, np.int32)
    )


def step_requests(frame, bits):
    return [SaveGameState(frame), adv(bits)]


def rollback_requests(load, corrected):
    """[Load, (Save, Adv)×k] replaying ``corrected`` from frame ``load``."""
    reqs = [LoadGameState(load)]
    for t, bits in enumerate(corrected):
        reqs += [SaveGameState(load + t), adv(bits)]
    return reqs


class ChecksumLog:
    def __init__(self):
        self.seen = {}

    def report_checksum(self, frame, cs):
        self.seen[frame] = int(cs)


def run_both(serial, spec, script):
    """Apply the same request script to both runners (spec speculates when
    the script says so); returns their checksum logs."""
    logs = (ChecksumLog(), ChecksumLog())
    for item in script:
        if item[0] == "reqs":
            serial.handle_requests(item[1], logs[0])
            spec.handle_requests(item[1], logs[1])
        elif item[0] == "speculate":
            spec.speculate(item[1])
    assert serial.frame == spec.frame
    assert combine64(checksum(serial.state)) == combine64(checksum(spec.state))
    assert logs[0].seen == logs[1].seen
    return logs


def test_full_span_hit():
    # Frames 0..2 advance normally; speculate from anchor 3 (confirmed=2);
    # frames 3,4 advance (predicted); rollback Load(3) replays corrected
    # inputs that branch 2 of the tensor predicts exactly.
    corrected = np.array([[[1, 4]], [[1, 8]], [[1, 2]]], np.uint8).reshape(3, P)
    tensor = np.zeros((4, 4, P), np.uint8)
    tensor[2, :3] = corrected
    tensor[2, 3] = [9, 9]  # unused tail frame of the rollout
    serial, spec = make_runners(fixed_sampler(tensor), 4, 4)

    script = [("reqs", step_requests(f, [f, f + 1])) for f in range(3)]
    script.append(("speculate", 2))
    script.append(("reqs", step_requests(3, [3, 4])))
    script.append(("reqs", step_requests(4, [4, 5])))
    script.append(("reqs", rollback_requests(3, list(corrected))))
    run_both(serial, spec, script)
    assert spec.spec_hits == 1 and spec.spec_misses == 0


def test_miss_falls_back_serial():
    tensor = np.full((4, 4, P), 13, np.uint8)  # never matches
    serial, spec = make_runners(fixed_sampler(tensor), 4, 4)
    script = [("reqs", step_requests(f, [f, f + 1])) for f in range(3)]
    script.append(("speculate", 2))
    script.append(("reqs", step_requests(3, [3, 4])))
    script.append(("reqs", rollback_requests(3, [[5, 6], [6, 7]])))
    run_both(serial, spec, script)
    assert spec.spec_hits == 0 and spec.spec_misses == 1


def test_partial_span_hit_load_after_anchor():
    # Anchor 2 but rollback loads at 4: the branch must ALSO match the
    # as-used inputs for frames 2..3 for its trajectory to be valid.
    used = {2: [2, 3], 3: [3, 4]}
    corrected = [[11, 1], [12, 2]]
    tensor = np.zeros((2, 4, P), np.uint8)
    tensor[1, 0] = used[2]
    tensor[1, 1] = used[3]
    tensor[1, 2] = corrected[0]
    tensor[1, 3] = corrected[1]
    serial, spec = make_runners(fixed_sampler(tensor), 2, 4)
    script = [("reqs", step_requests(f, [f, f + 1])) for f in range(2)]
    script.append(("speculate", 1))  # anchor = 2
    for f in (2, 3, 4):
        script.append(("reqs", step_requests(f, used.get(f, [4, 5]))))
    script.append(("reqs", rollback_requests(4, corrected)))
    run_both(serial, spec, script)
    assert spec.spec_hits == 1


def test_trajectory_mismatch_before_load_is_a_miss():
    # Branch matches the corrected span but NOT the as-used frame between
    # anchor and load — committing it would adopt a wrong trajectory, so it
    # must miss.
    corrected = [[11, 1]]
    tensor = np.zeros((2, 4, P), np.uint8)
    tensor[1, 0] = [99, 99]  # contradicts as-used inputs of frame 2
    tensor[1, 1] = corrected[0]
    serial, spec = make_runners(fixed_sampler(tensor), 2, 4)
    script = [("reqs", step_requests(f, [f, f + 1])) for f in range(2)]
    script.append(("speculate", 1))  # anchor = 2
    script.append(("reqs", step_requests(2, [2, 3])))
    script.append(("reqs", step_requests(3, [3, 4])))
    script.append(("reqs", rollback_requests(3, corrected)))
    run_both(serial, spec, script)
    assert spec.spec_hits == 0 and spec.spec_misses == 1


def test_hit_through_rollout_end_uses_final_state():
    # Replay consumes the rollout's entire span: the committed state must be
    # the rollout's final state, not a ring slot.
    corrected = np.array([[5, 1], [6, 2], [7, 3], [8, 4]], np.uint8)
    tensor = np.zeros((2, 4, P), np.uint8)
    tensor[0] = corrected
    serial, spec = make_runners(fixed_sampler(tensor), 2, 4)
    script = [("reqs", step_requests(f, [f, f + 1])) for f in range(3)]
    script.append(("speculate", 2))  # anchor = 3, rollout covers 3..6
    for f in (3, 4, 5, 6):
        script.append(("reqs", step_requests(f, [f, f + 1])))
    script.append(("reqs", rollback_requests(3, list(corrected))))
    run_both(serial, spec, script)
    assert spec.spec_hits == 1


def test_partial_prefix_commit_resimulates_only_tail():
    # Branch matches the first 2 of 3 corrected frames: those 2 commit from
    # the rollout, only the third is resimulated — still bitwise equal.
    corrected = [[11, 1], [12, 2], [13, 3]]
    tensor = np.zeros((2, 4, P), np.uint8)
    tensor[1, 0] = corrected[0]
    tensor[1, 1] = corrected[1]
    tensor[1, 2] = [99, 99]  # diverges at the third replayed frame
    serial, spec = make_runners(fixed_sampler(tensor), 2, 4)
    script = [("reqs", step_requests(f, [f, f + 1])) for f in range(3)]
    script.append(("speculate", 2))  # anchor = 3
    script.append(("reqs", step_requests(3, [3, 4])))
    script.append(("reqs", step_requests(4, [4, 5])))
    script.append(("reqs", rollback_requests(3, corrected)))
    run_both(serial, spec, script)
    assert spec.spec_partial_hits == 1 and spec.spec_hits == 0
    assert spec.rollback_frames_recovered_total == 2
    assert spec.rollback_frames_total == 1  # only the tail frame re-ran


def test_sampler_path_with_session_pinning():
    """Custom sampler + a session exposing confirmed_input: pinning must
    produce a writable tensor (regression: read-only device-array view)
    and pinned slots must override the sampler across all branches."""
    class FakeSession:
        def confirmed_input(self, handle, frame):
            if frame <= 4:  # frames 3..4 confirmed for everyone
                return np.uint8(7 + handle)
            return None

    tensor = np.full((4, 4, P), 13, np.uint8)
    _, spec = make_runners(fixed_sampler(tensor), 4, 4)
    script = [("reqs", step_requests(f, [f, f + 1])) for f in range(3)]
    for item in script:
        spec.handle_requests(item[1], ChecksumLog())
    spec.speculate(2, FakeSession())  # anchor 3, span 3..6
    bits = np.asarray(spec._result.branch_bits)
    assert (bits[:, 0] == [7, 8]).all()  # frame 3 pinned
    assert (bits[:, 1] == [7, 8]).all()  # frame 4 pinned
    # Branch 0 is the session's forward-fill prediction: after the confirmed
    # mid-span change the unknown suffix repeats the LAST KNOWN value, not
    # the anchor-1 input (and not the sampler's variation).
    assert (bits[0, 2] == [7, 8]).all()
    # Other branches spend capacity on sampler variations of the unknowns.
    assert (bits[1:, 2] == 13).all()


def test_structured_base_forward_fills_known_changes():
    """A confirmed input change inside the span must carry forward into the
    unknown suffix (the session predicts repeat-LAST-CONFIRMED, not
    repeat-anchor-input) — otherwise branch 0 diverges from the session's
    own prediction."""
    _, spec = make_runners(None, num_branches=8, spec_frames=4)
    last = np.array([1, 2], np.uint8)
    known = np.zeros((4, P), np.uint8)
    known_mask = np.zeros((4, P), bool)
    known[0, 0] = 9  # player 0 confirmed a change to 9 at span frame 0
    known_mask[0, 0] = True
    bits = spec._structured_bits(last, known, known_mask)
    # Branch 0: player 0 holds the NEW confirmed value through the suffix;
    # player 1 repeats its anchor input.
    assert bits[0, :, 0].tolist() == [9, 9, 9, 9]
    assert bits[0, :, 1].tolist() == [2, 2, 2, 2]
    # Change branches never alter the pinned slot.
    assert (bits[:, 0, 0] == 9).all()


def _candidates_loop_oracle(spec, last):
    """Independent straight-Python candidate ranking: recent distinct
    as-used values (newest first), then press/release toggles of
    recently-changed bits, then the declared universe."""
    nP = spec.num_players
    shape = spec.input_spec.shape
    n_field = int(np.prod(shape, dtype=np.int64)) if shape else 1
    dtype = spec.input_spec.zeros_np(1).dtype
    lastf = np.asarray(last).reshape(nP, n_field)
    frames = sorted(spec._input_log)[-32:]
    rows = {}
    for h in range(nP):
        for k in range(n_field):
            seq = [
                int(np.asarray(spec._input_log[f]).reshape(nP, n_field)[h, k])
                for f in frames
            ]
            recent = []
            for v in reversed(seq):
                if v not in recent:
                    recent.append(v)
            toggles = []
            if np.issubdtype(dtype, np.integer):
                changed = 0
                for a, b in zip(seq, seq[1:]):
                    changed |= a ^ b
                top = max((int(v) for v in spec._branch_values), default=0)
                all_bits, bit = [], 1
                while bit <= max(changed, top):
                    all_bits.append(bit)
                    bit <<= 1
                for b in [x for x in all_bits if changed & x] + [
                    x for x in all_bits if not (changed & x)
                ]:
                    toggles.append(int(lastf[h, k]) ^ b)
            allowed = {int(v) for v in spec._branch_values}
            row = []
            for v in recent + toggles + [int(v) for v in spec._branch_values]:
                if v not in row and v in allowed:
                    row.append(v)
            rows[h, k] = row
    return rows


def _structured_bits_loop_oracle(spec, last, known, known_mask):
    """Straight-Python enumeration oracle for the vectorized builder:
    (candidate-rank, frame, player, field)-major over the history-ranked
    candidate rows, skipping pinned slots, rank padding, and values equal
    to the base prediction."""
    from bevy_ggrs_tpu.spec_runner import _forward_fill

    F, P_, B = spec.spec_frames, spec.num_players, spec.num_branches
    shape = spec.input_spec.shape
    base = _forward_fill(last, known, known_mask)
    out = np.broadcast_to(base, (B, F, P_) + shape).copy()
    rows = _candidates_loop_oracle(spec, last)
    max_r = max(len(r) for r in rows.values())
    b = 1
    frames_idx = np.arange(F)
    for r in range(max_r):
        for t in range(F):
            for h in range(P_):
                if known_mask[t, h]:
                    continue
                suffix = (frames_idx >= t) & ~known_mask[:, h]
                for k, field in enumerate(np.ndindex(shape)) if shape else [
                    (0, ())
                ]:
                    row = rows[h, k]
                    if r >= len(row):
                        continue
                    v = row[r]
                    if b >= B:
                        return out
                    if v == base[(t, h) + field]:
                        continue
                    out[(b,) + (suffix, h) + field] = v
                    b += 1
    return out


def test_structured_bits_vectorized_matches_loop_oracle():
    """The vectorized tree builder (round-3 verdict weak #5: the Python
    O(B·F) loop cost milliseconds per tick at the stress shape) must
    reproduce the loop enumeration bit-for-bit, including at the stress
    shape P=8, F=12, B=1024 — with and without input history driving the
    candidate ranking."""
    rng = np.random.RandomState(5)
    # Pinned predictor-OFF: the loop oracle models the heuristic
    # candidate ranking (seeded-tree parity lives in test_predictor.py).
    cases = [
        (4, 4, P, make_runners(None, 4, 4, predictor=False)[1]),
        (96, 4, P, None),
    ]
    for B, F, nP, spec in cases + [(1024, 12, 8, None)]:
        if spec is None:
            spec = SpeculativeRollbackRunner(
                box_game.make_schedule(),
                box_game.make_world(nP).commit(),
                max_prediction=12, num_players=nP,
                input_spec=box_game.INPUT_SPEC,
                num_branches=B, spec_frames=F, predictor=False,
            )
        last = rng.randint(0, 16, (nP,)).astype(np.uint8)
        known = rng.randint(0, 16, (F, nP)).astype(np.uint8)
        mask = rng.rand(F, nP) < 0.4
        got = spec._structured_bits(last, known, mask)
        want = _structured_bits_loop_oracle(spec, last, known, mask)
        assert np.array_equal(got, want), (B, F, nP)
        # With as-used history: recency + toggle ranking kicks in.
        for f in range(6):
            spec._input_log[f] = rng.randint(0, 16, (nP,)).astype(np.uint8)
        got = spec._structured_bits(last, known, mask)
        want = _structured_bits_loop_oracle(spec, last, known, mask)
        assert np.array_equal(got, want), ("hist", B, F, nP)
        spec._input_log.clear()
    # Degenerate: everything pinned -> every branch is the base prediction.
    spec = make_runners(None, 4, 4)[1]
    last = np.array([1, 2], np.uint8)
    known = np.full((4, P), 5, np.uint8)
    mask = np.ones((4, P), bool)
    bits = spec._structured_bits(last, known, mask)
    assert (bits == bits[0]).all()


def test_candidate_ranking_prioritizes_recent_and_toggles():
    """Projectiles' live failure mode (round-4 verdict item 2): a player
    alternating UP <-> UP|FIRE in a 32-value universe. The candidate row
    must lead with the recent working set, so the FIRE transition is
    covered at EVERY frame by a small tree."""
    from bevy_ggrs_tpu.models import projectiles

    # Pinned predictor-OFF: this asserts the HEURISTIC ranking's shape
    # (a learned ranking is free to order the row differently).
    spec = SpeculativeRollbackRunner(
        box_game.make_schedule(), box_game.make_world(2).commit(),
        max_prediction=8, num_players=2,
        input_spec=projectiles.INPUT_SPEC, num_branches=64,
        predictor=False,
    )
    UP, FIRE = projectiles.INPUT_UP, projectiles.INPUT_FIRE
    # Irregular (APERIODIC) fire tapping: the periodic extrapolator must
    # not trigger, leaving coverage to the transition-ranked tree.
    pattern = [0, 0, 0, 1, 1, 0, 0, 0, 0, 1, 1, 1]
    for f, fire in enumerate(pattern):
        bits = np.array([UP | (FIRE if fire else 0), 0], np.uint8)
        spec._input_log[f] = bits
    last = np.array([UP, 0], np.uint8)
    C, valid = spec._candidate_values(last)
    row0 = [int(v) for v in C[0, 0][valid[0, 0]]]
    # Player 0's top candidates are its two recent values; UP|FIRE (the
    # transition from last=UP) ranks in the top two.
    assert (UP | FIRE) in row0[:2]
    # The tree therefore covers the FIRE press at every unknown frame:
    known = np.zeros((8, 2), np.uint8)
    mask = np.zeros((8, 2), bool)
    tree = spec._structured_bits(last, known, mask)
    for t in range(8):
        wanted = np.broadcast_to(last, (8, 2)).copy()
        wanted[t:, 0] = UP | FIRE
        assert any(
            np.array_equal(tree[b], wanted) for b in range(64)
        ), f"FIRE press at frame {t} not enumerated"


def test_confirmed_span_bulk_query_matches_getter():
    """P2PSession.confirmed_span (one call per player per tick) must agree
    with the per-frame confirmed_input getter on both queue backends —
    it is what _known_inputs now pins branches with."""
    from tests.test_p2p import FPS_DT, make_pair, scripted_input
    from bevy_ggrs_tpu.session import PredictionThreshold, SessionState
    from bevy_ggrs_tpu.transport.loopback import LoopbackNetwork

    net = LoopbackNetwork(latency=2 * FPS_DT, seed=3)
    peers = make_pair(net)
    for _ in range(40):
        net.advance(FPS_DT)
        for session, runner in peers:
            session.poll_remote_clients()
            if session.current_state() != SessionState.RUNNING:
                continue
            for h in session.local_player_handles():
                session.add_local_input(
                    h, scripted_input(h, session.current_frame)
                )
            try:
                runner.handle_requests(session.advance_frame(), session)
            except PredictionThreshold:
                continue
    session, _ = peers[0]
    anchor = session.confirmed_frame() - 3
    for h in range(P):
        vals, mask = session.confirmed_span(h, anchor, 8)
        assert mask.any() and not mask.all()  # straddles the frontier
        for i in range(8):
            got = session.confirmed_input(h, anchor + i)
            assert mask[i] == (got is not None)
            if got is not None:
                assert np.array_equal(vals[i], got)


def test_loopback_session_equivalence():
    """Full P2P run: peer 0 speculating must produce exactly the checksum
    stream of the all-serial universe (hits or not)."""
    from tests.test_p2p import (
        FPS_DT, common_confirmed_checksums, make_pair, scripted_input,
    )
    from bevy_ggrs_tpu.session import PredictionThreshold, SessionState
    from bevy_ggrs_tpu.transport.loopback import LoopbackNetwork

    def drive_universe(speculate: bool):
        net = LoopbackNetwork(latency=2.5 * FPS_DT, seed=11)
        peers = make_pair(net, max_prediction=8)
        if speculate:
            session0, _ = peers[0]
            spec_runner = SpeculativeRollbackRunner(
                box_game.make_schedule(), box_game.make_world(2).commit(),
                max_prediction=8, num_players=2,
                input_spec=box_game.INPUT_SPEC,
                num_branches=16, spec_frames=8, seed=3,
            )
            peers[0] = (session0, spec_runner)
        for _ in range(60):
            net.advance(FPS_DT)
            for session, runner in peers:
                session.poll_remote_clients()
                if session.current_state() != SessionState.RUNNING:
                    continue
                for h in session.local_player_handles():
                    session.add_local_input(
                        h, scripted_input(h, session.current_frame)
                    )
                try:
                    requests = session.advance_frame()
                except PredictionThreshold:
                    continue
                runner.handle_requests(requests, session)
                if isinstance(runner, SpeculativeRollbackRunner):
                    runner.speculate(session.confirmed_frame())
        return peers

    serial_peers = drive_universe(False)
    spec_peers = drive_universe(True)
    f1, cs1 = common_confirmed_checksums(serial_peers)
    f2, cs2 = common_confirmed_checksums(spec_peers)
    assert f1 and f1 == f2
    # Within each universe both peers agree; across universes identical.
    assert all(a == b for a, b in cs1)
    assert all(a == b for a, b in cs2)
    assert cs1 == cs2
    spec_runner = spec_peers[0][1]
    assert spec_runner.rollbacks_total > 0  # rollbacks actually happened


def test_meshed_live_speculation_equivalent_and_distributed():
    """A SpeculativeRollbackRunner built with a mesh lays the branch axis
    over it for LIVE speculation (not just the standalone executor) and
    keeps the world entity-sharded — and the P2P outcome is bitwise the
    unmeshed universe's."""
    import jax

    from bevy_ggrs_tpu.parallel.sharding import branch_mesh
    from tests.test_p2p import (
        FPS_DT, common_confirmed_checksums, make_pair, scripted_input,
    )
    from bevy_ggrs_tpu.session import PredictionThreshold, SessionState
    from bevy_ggrs_tpu.transport.loopback import LoopbackNetwork

    if len(jax.devices()) < 2:
        import pytest

        pytest.skip("needs a multi-device mesh")

    def drive(mesh):
        net = LoopbackNetwork(latency=2.5 * FPS_DT, seed=21)
        peers = make_pair(net, max_prediction=8)
        session0, _ = peers[0]
        spec = SpeculativeRollbackRunner(
            box_game.make_schedule(), box_game.make_world(2).commit(),
            max_prediction=8, num_players=2, input_spec=box_game.INPUT_SPEC,
            num_branches=16, spec_frames=8, seed=3, mesh=mesh,
        )
        peers[0] = (session0, spec)
        for _ in range(50):
            net.advance(FPS_DT)
            for session, runner in peers:
                session.poll_remote_clients()
                if session.current_state() != SessionState.RUNNING:
                    continue
                for h in session.local_player_handles():
                    session.add_local_input(
                        h, scripted_input(h, session.current_frame)
                    )
                try:
                    requests = session.advance_frame()
                except PredictionThreshold:
                    continue
                runner.handle_requests(requests, session)
                if isinstance(runner, SpeculativeRollbackRunner):
                    runner.speculate(session.confirmed_frame(), session)
        return peers, spec

    mesh = branch_mesh()  # all devices on the branch axis
    meshed_peers, meshed_spec = drive(mesh)
    plain_peers, _ = drive(None)

    # Live rollouts really were distributed over the mesh.
    assert meshed_spec._result is not None
    leaf = meshed_spec._result.checksums
    assert not leaf.sharding.is_fully_replicated
    assert meshed_spec.rollbacks_total > 0

    f1, cs1 = common_confirmed_checksums(meshed_peers)
    f2, cs2 = common_confirmed_checksums(plain_peers)
    assert f1 and f1 == f2 and cs1 == cs2


def test_speculate_dedups_identical_redispatch():
    """Ticks where the confirmed frontier hasn't moved and no new inputs
    confirmed inside the span must NOT re-dispatch the (identical) rollout;
    anything that changes the prediction inputs must."""

    class FakeSession:
        def __init__(self):
            self.inputs = {}

        def confirmed_input(self, handle, frame):
            return self.inputs.get((handle, frame))

    _, spec = make_runners(num_branches=4, spec_frames=4)
    session = FakeSession()
    # Advance to frame 4 so a past anchor exists.
    for f in range(4):
        spec.handle_requests(step_requests(f, [f, f + 1]), None)

    spec.speculate(1, session)  # anchor 2 < frame 4: dedup eligible
    first = spec._result
    assert first is not None and spec.spec_dispatches_skipped == 0
    spec.speculate(1, session)  # identical tick -> skipped
    assert spec.spec_dispatches_skipped == 1
    assert spec._result is first
    # A newly confirmed input inside the span changes the signature.
    session.inputs[(1, 3)] = np.uint8(9)
    spec.speculate(1, session)
    assert spec.spec_dispatches_skipped == 1
    assert spec._result is not first
    # Frontier advance changes the anchor -> re-dispatch.
    second = spec._result
    spec.speculate(2, session)
    assert spec._result is not second
    # Live-state anchor (anchor == frame) never dedups: state moves.
    spec.speculate(3, session)
    live1 = spec._result
    spec.speculate(3, session)
    assert spec._result is not live1


def test_restore_invalidates_speculative_transients(tmp_path):
    """A checkpoint restore replaces ring/state/frame from outside the
    request protocol; the pending rollout, its dedup signature, and the
    as-used input log describe the pre-restore world and must be dropped
    (code-review r3: the dedup otherwise serves a pre-restore rollout
    indefinitely)."""
    from bevy_ggrs_tpu.utils.persistence import restore_runner, save_runner

    _, spec = make_runners(num_branches=4, spec_frames=4)
    for f in range(3):
        spec.handle_requests(step_requests(f, [f, f + 1]), None)
    path = str(tmp_path / "ck.npz")
    save_runner(path, spec)
    spec.handle_requests(step_requests(3, [3, 4]), None)
    spec.speculate(2)
    assert spec._result is not None and spec._input_log

    restore_runner(path, spec)
    assert spec._result is None
    assert spec._spec_sig is None
    assert not spec._input_log
    assert spec.frame == 3


def test_random_sampler_path_never_dedups():
    """Each sampler dispatch draws fresh Monte Carlo branches — skipping a
    'same-signature' tick would collapse the compounding hit probability,
    so the dedup must bypass sampler-based runners entirely."""
    from bevy_ggrs_tpu.parallel.speculate import bitmask_sampler

    _, spec = make_runners(num_branches=4, spec_frames=4)
    spec._sampler = bitmask_sampler()
    for f in range(4):
        spec.handle_requests(step_requests(f, [f, f + 1]), None)
    spec.speculate(1)
    first = spec._result
    spec.speculate(1)
    assert spec._result is not first  # fresh draw, no skip
    assert spec.spec_dispatches_skipped == 0


def test_periodic_extrapolation_covers_multi_player_cycles():
    """Two remote players cycling keys every 3 frames (the projectiles
    live workload): repeat-last mispredicts every boundary and a span
    contains boundaries from BOTH players — unreachable for single-change
    branches. The periodic extrapolation base must predict both players'
    continuations exactly, so branch 1 matches the true future."""
    spec = SpeculativeRollbackRunner(
        box_game.make_schedule(), box_game.make_world(2).commit(),
        max_prediction=8, num_players=2,
        input_spec=box_game.INPUT_SPEC, num_branches=16, spec_frames=8,
    )
    keys = [1, 2, 4, 0]

    def scripted(h, f):
        return keys[(f // 3 + h) % 4]

    for f in range(40):
        spec._input_log[f] = np.array(
            [scripted(0, f), scripted(1, f)], np.uint8
        )
    anchor = 40
    last = spec._input_log[anchor - 1]
    known = np.zeros((8, 2), np.uint8)
    mask = np.zeros((8, 2), bool)
    tree = spec._structured_bits(last, known, mask, anchor)
    truth = np.array(
        [[scripted(h, anchor + t) for h in range(2)] for t in range(8)],
        np.uint8,
    )
    # Branch 0 stays the session's forward-fill prediction...
    assert np.array_equal(tree[0], np.broadcast_to(last, (8, 2)))
    # ...and branch 1 IS the true periodic future for both players.
    assert np.array_equal(tree[1], truth), (tree[1], truth)


def test_extrapolation_falls_back_without_periodicity():
    """Aperiodic history must leave the tree identical to the plain
    forward-fill single-change enumeration (no wasted branch 1)."""
    rng = np.random.RandomState(9)
    spec = SpeculativeRollbackRunner(
        box_game.make_schedule(), box_game.make_world(2).commit(),
        max_prediction=8, num_players=2,
        input_spec=box_game.INPUT_SPEC, num_branches=16, spec_frames=8,
    )
    for f in range(40):
        spec._input_log[f] = rng.randint(0, 16, (2,)).astype(np.uint8)
    last = spec._input_log[39]
    known = np.zeros((8, 2), np.uint8)
    mask = np.zeros((8, 2), bool)
    tree = spec._structured_bits(last, known, mask, 40)
    base = np.broadcast_to(last, (8, 2))
    assert np.array_equal(tree[0], base)
    assert not np.array_equal(tree[1], tree[0])  # a real change branch
