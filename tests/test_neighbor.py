"""Neighbor-grid subsystem (ops/neighbor.py + ops/cell_gather.py).

Five claims under test, matching the module's determinism contract:

1. Binning is bitwise-reproducible and *specified*: a pure-NumPy oracle
   twin reproduces slots/spill/occupancy/drop counters exactly (integer
   equality), including the overflow and drop regimes.
2. Grid-mode forces agree with the dense path within float tolerance
   (different summation association — allclose, never bitwise), for both
   the XLA and the Pallas cell-gather per-cell implementations.
3. Interactions whose pair terms are 0/1 indicators (the projectile hit
   test) agree with dense BITWISE — whole-state equality across an
   80-step spawn/despawn episode, and under SyncTest forced rollbacks
   (despawn/respawn masking mid-rollback).
4. Within grid mode the serial, fused-speculative (attestation) and
   entity-sharded executables are bitwise-equal to each other.
5. Mode resolution precedence: explicit > GGRS_FORCE_MODE env >
   SessionBuilder default > auto-threshold > legacy dense.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bevy_ggrs_tpu.models import boids
from bevy_ggrs_tpu.models import projectiles as pj
from bevy_ggrs_tpu.ops import neighbor
from bevy_ggrs_tpu.runner import RollbackRunner
from bevy_ggrs_tpu.schedule import make_inputs
from bevy_ggrs_tpu.session import SyncTestSession


@pytest.fixture(autouse=True)
def _clear_session_default():
    yield
    neighbor.set_default_interaction_mode(None)


def oracle_bin(pos, active, cfg):
    """NumPy twin of neighbor.bin_entities — same float ops (f32 multiply,
    floor, int32 mod), same stable order, pure host code."""
    n = pos.shape[0]
    g, c = cfg.grid_dim, cfg.num_cells
    k, s = cfg.cell_capacity, cfg.spill_capacity
    inv = np.float32(1.0 / cfg.cell_size)
    ix = np.floor(pos[:, 0].astype(np.float32) * inv).astype(np.int32) % g
    iy = np.floor(pos[:, 1].astype(np.float32) * inv).astype(np.int32) % g
    cell = np.where(active.astype(bool), iy * g + ix, c).astype(np.int32)
    order = np.argsort(cell, kind="stable").astype(np.int32)
    sc = cell[order]
    rank = np.arange(n) - np.searchsorted(sc, sc, side="left")
    slots = np.full((c, k), n, np.int32)
    slotted = (sc < c) & (rank < k)
    slots[sc[slotted], rank[slotted]] = order[slotted]
    over = (sc < c) & (rank >= k)
    ov = order[over]
    spill = np.full(s, n, np.int32)
    spill[: min(len(ov), s)] = ov[:s]
    occ = np.bincount(sc[sc < c], minlength=c)[:c].astype(np.int32)
    n_spilled = int(over.sum())
    return slots, spill, cell, occ, n_spilled, max(n_spilled - s, 0)


def rand_world(n, seed=0, spread=8.0):
    rng = np.random.RandomState(seed)
    pos = rng.uniform(-spread, spread, size=(n, 2)).astype(np.float32)
    vel = rng.uniform(-0.05, 0.05, size=(n, 2)).astype(np.float32)
    active = np.ones(n, bool)
    active[rng.choice(n, size=n // 8, replace=False)] = False
    return pos, vel, active


def assert_matches_oracle(pos, active, cfg):
    g = neighbor.bin_entities(jnp.asarray(pos), jnp.asarray(active), cfg)
    slots, spill, cell, occ, n_spilled, n_dropped = oracle_bin(
        pos, active, cfg
    )
    np.testing.assert_array_equal(np.asarray(g.slots), slots)
    np.testing.assert_array_equal(np.asarray(g.spill), spill)
    np.testing.assert_array_equal(np.asarray(g.cell_of), cell)
    np.testing.assert_array_equal(np.asarray(g.occupancy), occ)
    assert int(g.n_spilled) == n_spilled
    assert int(g.n_dropped) == n_dropped


class TestBinning:
    def test_matches_numpy_oracle(self):
        pos, _, active = rand_world(700, seed=3)
        assert_matches_oracle(pos, active, boids.grid_config(700))

    def test_oracle_parity_beyond_world_bounds(self):
        """Spawn-spiral positions exceed ±WORLD_HALF at scale; binning must
        stay well-defined (mod-wrap aliasing) and oracle-exact there."""
        rng = np.random.RandomState(9)
        pos = rng.uniform(-60, 60, size=(900, 2)).astype(np.float32)
        active = rng.rand(900) > 0.2
        assert_matches_oracle(pos, active, boids.grid_config(900))

    def test_oracle_parity_under_overflow_and_drop(self):
        """Clustered world: cells overflow into spill, spill overflows into
        counted drops — the oracle reproduces both regimes exactly."""
        rng = np.random.RandomState(5)
        pos = (rng.uniform(-0.4, 0.4, size=(64, 2))).astype(np.float32)
        active = np.ones(64, bool)
        cfg = neighbor.GridConfig(
            cell_size=1.0, grid_dim=4, cell_capacity=4, spill_capacity=8
        )
        g = neighbor.bin_entities(jnp.asarray(pos), jnp.asarray(active), cfg)
        assert int(g.n_spilled) > 8 and int(g.n_dropped) > 0
        assert_matches_oracle(pos, active, cfg)

    def test_inactive_entities_reach_neither_slots_nor_spill(self):
        pos, _, active = rand_world(300, seed=7)
        cfg = boids.grid_config(300)
        g = neighbor.bin_entities(jnp.asarray(pos), jnp.asarray(active), cfg)
        slots = np.asarray(g.slots)
        members = set(slots[slots < 300].tolist())
        spill = np.asarray(g.spill)
        members |= set(spill[spill < 300].tolist())
        assert members == set(np.where(active)[0].tolist())
        assert np.all(np.asarray(g.cell_of)[~active] == cfg.num_cells)

    def test_default_config_shapes(self):
        cfg = boids.grid_config(32768)
        assert cfg.grid_dim == 16  # pow2 covering the ±8 torus at s=1
        assert cfg.cell_capacity % 8 == 0
        assert cfg.padded_cols % 128 == 0
        with pytest.raises(ValueError):
            neighbor.GridConfig(
                cell_size=1.0, grid_dim=2, cell_capacity=4, spill_capacity=4
            )

    def test_cell_size_below_radius_rejected(self):
        pos, vel, active = rand_world(64)
        cfg = neighbor.GridConfig(
            cell_size=0.5, grid_dim=16, cell_capacity=8, spill_capacity=8
        )
        with pytest.raises(ValueError, match="radius"):
            neighbor.interact(
                jnp.asarray(pos), jnp.asarray(active),
                boids.FLOCK_PAIR_KERNEL,
                {"vx": jnp.asarray(vel[:, 0]), "vy": jnp.asarray(vel[:, 1])},
                mode="grid", config=cfg,
            )

    def test_grid_stats_keys(self):
        pos, _, active = rand_world(500)
        stats = neighbor.grid_stats(pos, active, boids.grid_config(500))
        for key in ("occupancy_mean", "occupancy_max", "spill_rate",
                    "dropped", "slot_utilization"):
            assert key in stats
        assert stats["dropped"] == 0


class TestForces:
    def _forces(self, pos, vel, active, **kw):
        return neighbor.interact(
            jnp.asarray(pos), jnp.asarray(active), boids.FLOCK_PAIR_KERNEL,
            {"vx": jnp.asarray(vel[:, 0]), "vy": jnp.asarray(vel[:, 1])},
            **kw,
        )

    def test_dense_matches_legacy_reference(self):
        """The PairKernel dense path must reproduce pairwise_force_rows —
        same terms, same masks — to float tolerance."""
        pos, vel, active = rand_world(400, seed=1)
        af = active.astype(np.float32)
        ref = boids.pairwise_force_rows(
            jnp.asarray(pos), jnp.asarray(vel), jnp.asarray(pos),
            jnp.asarray(vel), jnp.asarray(af), jnp.asarray(af),
        )
        got = self._forces(pos, vel, active, mode="dense")
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=1e-6)

    @pytest.mark.parametrize("impl", ["xla", "pallas"])
    def test_grid_matches_dense(self, impl):
        pos, vel, active = rand_world(1500, seed=2)
        cfg = boids.grid_config(1500)
        dense = self._forces(pos, vel, active, mode="dense")
        grid, g = self._forces(pos, vel, active, mode="grid", config=cfg,
                               impl=impl, return_grid=True)
        assert int(g.n_dropped) == 0
        np.testing.assert_allclose(np.asarray(grid), np.asarray(dense),
                                   atol=1e-5)
        assert np.all(np.asarray(grid)[~active] == 0.0)

    def test_spill_fallback_preserves_forces(self):
        """Overflowed cells degrade to the dense [S, N] pass, not to wrong
        values: a clustered world with most entities spilled still matches
        dense."""
        rng = np.random.RandomState(11)
        pos = rng.uniform(-0.45, 0.45, size=(48, 2)).astype(np.float32)
        vel = rng.uniform(-0.05, 0.05, size=(48, 2)).astype(np.float32)
        active = np.ones(48, bool)
        cfg = neighbor.GridConfig(
            cell_size=1.0, grid_dim=4, cell_capacity=4, spill_capacity=48
        )
        dense = self._forces(pos, vel, active, mode="dense")
        grid, g = self._forces(pos, vel, active, mode="grid", config=cfg,
                               return_grid=True)
        assert int(g.n_spilled) > 0 and int(g.n_dropped) == 0
        np.testing.assert_allclose(np.asarray(grid), np.asarray(dense),
                                   atol=1e-5)

    def test_dropped_entities_get_zero_force(self):
        rng = np.random.RandomState(13)
        pos = rng.uniform(-0.45, 0.45, size=(48, 2)).astype(np.float32)
        vel = rng.uniform(-0.05, 0.05, size=(48, 2)).astype(np.float32)
        active = np.ones(48, bool)
        cfg = neighbor.GridConfig(
            cell_size=1.0, grid_dim=4, cell_capacity=4, spill_capacity=4
        )
        grid, g = self._forces(pos, vel, active, mode="grid", config=cfg,
                               return_grid=True)
        assert int(g.n_dropped) > 0
        slots = np.asarray(g.slots)
        placed = set(slots[slots < 48].tolist())
        spill = np.asarray(g.spill)
        placed |= set(spill[spill < 48].tolist())
        dropped = sorted(set(range(48)) - placed)
        assert len(dropped) == int(g.n_dropped)
        np.testing.assert_array_equal(np.asarray(grid)[dropped], 0.0)


class TestProjectilesBitwise:
    def test_dense_vs_grid_bitwise_over_lifecycle(self):
        """0/1 indicator sums are exact in f32, so the hit decision — and
        therefore the whole spawn/despawn state evolution — is bitwise
        mode-invariant."""
        state = pj.make_world(2, capacity=64).commit()
        sched_d = pj.make_schedule(mode="dense")
        sched_g = pj.make_schedule(mode="grid")

        @functools.partial(jax.jit, static_argnums=1)
        def step(s, sched, bits):
            return sched(s, make_inputs(bits))

        rng = np.random.RandomState(1)
        s_d = s_g = state
        saw_projectile = False
        for _ in range(80):
            bits = jnp.asarray(rng.randint(0, 32, size=2), jnp.uint8)
            s_d = step(s_d, sched_d, bits)
            s_g = step(s_g, sched_g, bits)
            saw_projectile = saw_projectile or bool(
                np.asarray(s_d.alive).sum() > 2
            )
        assert saw_projectile
        for a, b in zip(jax.tree_util.tree_leaves(s_d),
                        jax.tree_util.tree_leaves(s_g)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert np.asarray(s_d.resources["score"]).sum() > 0

    def test_synctest_despawn_respawn_under_forced_rollbacks_grid(self):
        """Grid-mode despawn/respawn masking mid-rollback: SyncTest
        resimulates every frame from check_distance back, so rolled-back
        spawns/despawns must rebin identically or the checksum trips."""
        session = SyncTestSession(
            2, pj.INPUT_SPEC, check_distance=5, max_prediction=8
        )
        runner = RollbackRunner(
            pj.make_schedule(mode="grid"),
            pj.make_world(2, capacity=32).commit(),
            max_prediction=8,
            num_players=2,
            input_spec=pj.INPUT_SPEC,
        )
        saw_projectile = False
        for frame in range(60):  # raises MismatchedChecksum on any desync
            for h in range(2):
                bits = pj.INPUT_FIRE if (frame + h) % 3 == 0 else (
                    pj.INPUT_RIGHT if h == 0 else pj.INPUT_UP
                )
                session.add_local_input(h, np.uint8(bits))
            runner.handle_requests(session.advance_frame(), session)
            host_alive = np.asarray(runner.state.alive)
            saw_projectile = saw_projectile or host_alive.sum() > 2
        assert runner.frame == 60
        assert saw_projectile


class TestCrossExecutable:
    def test_serial_vs_sharded_grid_bitwise(self):
        """Grid-mode twin of tests/test_sharded_midscale.py: the cell-slice
        sharded path (all-gathered slot-force concat, no float psum) must
        match the unsharded grid executable bitwise."""
        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device virtual mesh")
        from bevy_ggrs_tpu.parallel.sharding import branch_mesh, shard_world
        from bevy_ggrs_tpu.rollout import advance_n
        from bevy_ggrs_tpu.state import checksum, combine64

        sched = boids.make_schedule(kernel="xla", mode="grid")
        state = boids.make_world(4096, 2).commit()
        bits = jnp.asarray(np.array([[1, 2], [4, 8], [0, 3]], np.uint8))

        plain = advance_n(sched, state, bits)
        mesh = branch_mesh(entity_shards=8)
        sharded = advance_n(sched, shard_world(state, mesh, "entity"), bits)

        assert combine64(checksum(plain)) == combine64(checksum(sharded))
        for a, b in zip(jax.tree_util.tree_leaves(plain),
                        jax.tree_util.tree_leaves(sharded)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_sharded_grid_system_bitwise(self):
        """make_sharded_flock_system(mode="grid") — replicated binning +
        per-shard cell slices — matches the serial grid system bitwise."""
        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device virtual mesh")
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:8]), ("entity",))
        state = boids.make_world(4096, 2).commit()
        serial = boids.make_schedule(kernel="xla", mode="grid")
        shard = boids.make_sharded_schedule(
            mesh, "entity", kernel="xla", mode="grid"
        )

        @functools.partial(jax.jit, static_argnums=1)
        def step(s, sched, bits):
            return sched(s, make_inputs(bits))

        s1 = s2 = state
        for f in range(3):
            bits = jnp.asarray([f % 16, (f * 7) % 16], jnp.uint8)
            s1 = step(s1, serial, bits)
            s2 = step(s2, shard, bits)
        for name in ("position", "velocity"):
            np.testing.assert_array_equal(
                np.asarray(s1.components[name]),
                np.asarray(s2.components[name]),
            )

    @pytest.mark.parametrize(
        "make", [
            lambda: (boids.make_schedule(kernel="xla", mode="grid"),
                     boids.make_world(256, 2).commit(), boids.INPUT_SPEC),
            lambda: (pj.make_schedule(mode="grid"),
                     pj.make_world(2, capacity=32).commit(), pj.INPUT_SPEC),
        ],
        ids=["boids_grid", "projectiles_grid"],
    )
    def test_attestation_holds_in_grid_mode(self, make):
        """Serial-burst vs vmapped-speculative bitwise equality (the
        attestation machinery) with the binning inside the step."""
        from bevy_ggrs_tpu.spec_runner import (
            SpeculativeRollbackRunner,
            attest_speculation_safety,
        )

        sched, state, spec = make()
        runner = SpeculativeRollbackRunner(
            sched, state, max_prediction=8, num_players=2,
            input_spec=spec, num_branches=8, spec_frames=4,
        )
        report = attest_speculation_safety(runner)
        assert report.ok


class TestModeResolution:
    def test_explicit_always_wins(self, monkeypatch):
        monkeypatch.setenv("GGRS_FORCE_MODE", "grid")
        assert neighbor.resolve_mode("dense", 10**6) == "dense"
        monkeypatch.setenv("GGRS_FORCE_MODE", "dense")
        assert neighbor.resolve_mode("grid", 4) == "grid"

    def test_env_overrides_auto_and_legacy_default(self, monkeypatch):
        monkeypatch.setenv("GGRS_FORCE_MODE", "grid")
        assert neighbor.resolve_mode(None, 4) == "grid"
        assert neighbor.resolve_mode("auto", 4) == "grid"
        monkeypatch.delenv("GGRS_FORCE_MODE")
        assert neighbor.resolve_mode(None, 10**6) == "dense"

    def test_auto_threshold(self, monkeypatch):
        monkeypatch.delenv("GGRS_FORCE_MODE", raising=False)
        t = neighbor.GRID_AUTO_THRESHOLD
        assert neighbor.resolve_mode("auto", t - 1) == "dense"
        assert neighbor.resolve_mode("auto", t) == "grid"

    def test_session_builder_default(self, monkeypatch):
        monkeypatch.delenv("GGRS_FORCE_MODE", raising=False)
        from bevy_ggrs_tpu.session import SessionBuilder

        SessionBuilder().with_interaction_mode("grid")
        assert neighbor.resolve_mode(None, 4) == "grid"
        # env still outranks the session default for non-explicit modes
        monkeypatch.setenv("GGRS_FORCE_MODE", "dense")
        assert neighbor.resolve_mode(None, 4) == "dense"
        neighbor.set_default_interaction_mode(None)
        monkeypatch.delenv("GGRS_FORCE_MODE")
        assert neighbor.resolve_mode(None, 4) == "dense"

    def test_invalid_mode_rejected(self):
        with pytest.raises(ValueError):
            neighbor.resolve_mode("sparse", 4)
        with pytest.raises(ValueError):
            neighbor.set_default_interaction_mode("sparse")
