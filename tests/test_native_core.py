"""Parity tests: native C++ session core vs the pure-Python data plane.

The native core (``native/session_core.cpp``) must be semantically identical
to the Python ``InputQueue`` / tracker logic it replaces — same outputs, same
exceptions, same request streams. These tests drive both through randomized
op sequences and a full SyncTest session and assert bit-for-bit agreement.
"""

import numpy as np
import pytest

from bevy_ggrs_tpu.native import core as ncore
from bevy_ggrs_tpu.session.common import InvalidRequest
from bevy_ggrs_tpu.session.input_queue import InputQueue

native = pytest.mark.skipif(
    not ncore.available(), reason="native session core did not build"
)


@native
def test_queue_basic_parity():
    for shape, dtype in [((), np.uint8), ((3,), np.int16), ((2, 2), np.uint32)]:
        zero = np.zeros(shape, dtype)
        nq = ncore.NativeQueueSet(zero, [2]).queues[0]
        pq = InputQueue(zero, 2)
        rng = np.random.RandomState(0)
        for frame in range(30):
            bits = rng.randint(0, 100, size=shape).astype(dtype)
            assert nq.add_local_input(frame, bits) == pq.add_local_input(
                frame, bits
            )
            for f in range(frame + 4):
                nb, nc = nq.input(f)
                pb, pc = pq.input(f)
                assert nc == pc and np.array_equal(nb, pb), (shape, frame, f)
                got_n, got_p = nq.confirmed(f), pq.confirmed(f)
                assert (got_n is None) == (got_p is None)
                if got_n is not None:
                    assert np.array_equal(got_n, got_p)
            assert nq.last_confirmed_frame == pq.last_confirmed_frame


@native
def test_queue_stale_and_gap_parity():
    zero = np.zeros((), np.uint8)
    nq = ncore.NativeQueueSet(zero, [0]).queues[0]
    pq = InputQueue(zero, 0)
    assert nq.add_input(0, 7) == pq.add_input(0, 7) == 0
    # Stale (duplicate) frames are ignored in both.
    assert nq.add_input(0, 9) is None and pq.add_input(0, 9) is None
    # Gaps raise in both.
    with pytest.raises(InvalidRequest):
        nq.add_input(5, 1)
    with pytest.raises(InvalidRequest):
        pq.add_input(5, 1)


@native
def test_queue_discard_parity():
    zero = np.zeros((), np.uint8)
    nqs = ncore.NativeQueueSet(zero, [0])
    nq = nqs.queues[0]
    pq = InputQueue(zero, 0)
    for f in range(10):
        nq.add_input(f, f + 1)
        pq.add_input(f, f + 1)
    nqs.discard_before(6)
    pq.discard_before(6)
    for f in range(6, 10):
        assert np.array_equal(nq.confirmed(f), pq.confirmed(f))
    assert nq.confirmed(5) is None and pq.confirmed(5) is None
    with pytest.raises(InvalidRequest):
        nq.input(3)
    with pytest.raises(InvalidRequest):
        pq.input(3)
    # Prediction source survives the discard in both.
    nb, nc = nq.input(99)
    pb, pc = pq.input(99)
    assert not nc and not pc and np.array_equal(nb, pb)


@native
def test_gather_matches_python_loop():
    zero = np.zeros((2,), np.uint8)
    delays = [1, 0, 0]
    nqs = ncore.NativeQueueSet(zero, delays)
    pqs = ncore.PyQueueSet(zero, delays)
    rng = np.random.RandomState(1)
    disc = [2**31 - 1, 4, 2**31 - 1]  # player 1 disconnects at frame 4
    for frame in range(8):
        for h in range(3):
            bits = rng.randint(0, 255, size=(2,)).astype(np.uint8)
            if h == 1 and frame >= 4:
                continue  # disconnected: no more inputs
            nqs.queues[h].add_local_input(frame, bits)
            pqs.queues[h].add_local_input(frame, bits)
        nb, ns = nqs.gather(frame, disc)
        pb, ps = pqs.gather(frame, disc)
        assert np.array_equal(nb, pb) and np.array_equal(ns, ps), frame
    assert nqs.min_confirmed([1, 0, 1]) == pqs.min_confirmed([1, 0, 1])
    assert nqs.min_confirmed() == pqs.min_confirmed()


@native
def test_tracker_parity_randomized():
    zero = np.zeros((), np.uint8)
    nt = ncore.NativeTracker(2, zero)
    pt = ncore.PyTracker(2, zero)
    rng = np.random.RandomState(2)
    for step in range(200):
        op = rng.randint(0, 4)
        frame = int(rng.randint(0, 20))
        if op == 0:
            bits = rng.randint(0, 4, size=(2,)).astype(np.uint8)
            status = rng.randint(0, 2, size=(2,)).astype(np.int32)
            nt.record_used(frame, bits, status)
            pt.record_used(frame, bits, status)
        elif op == 1:
            h = int(rng.randint(0, 2))
            b = np.uint8(rng.randint(0, 4))
            nt.note_confirmed(h, frame, b)
            pt.note_confirmed(h, frame, b)
        elif op == 2:
            nt.clear_first_incorrect()
            pt.clear_first_incorrect()
        else:
            nt.discard_before(frame)
            pt.discard_before(frame)
        assert nt.first_incorrect == pt.first_incorrect, step
        got_n, got_p = nt.get_used(frame), pt.get_used(frame)
        assert (got_n is None) == (got_p is None)
        if got_n is not None:
            assert np.array_equal(got_n[0], got_p[0])
            assert np.array_equal(got_n[1], got_p[1])


@native
def test_synctest_request_stream_parity(monkeypatch):
    """A full SyncTest session produces identical request streams through
    the native and Python data planes."""
    from bevy_ggrs_tpu.session.requests import (
        AdvanceFrame,
        LoadGameState,
        SaveGameState,
    )
    from bevy_ggrs_tpu.session.synctest import SyncTestSession

    def run(force_py: bool):
        if force_py:
            monkeypatch.setattr(ncore, "available", lambda: False)
        else:
            monkeypatch.undo()
        sess = SyncTestSession(2, check_distance=3, max_prediction=8,
                               input_delay=1)
        rng = np.random.RandomState(3)
        stream = []
        for frame in range(12):
            for h in range(2):
                sess.add_local_input(h, np.uint8(rng.randint(0, 16)))
            for req in sess.advance_frame():
                if isinstance(req, SaveGameState):
                    stream.append(("save", req.frame))
                elif isinstance(req, LoadGameState):
                    stream.append(("load", req.frame))
                elif isinstance(req, AdvanceFrame):
                    stream.append(
                        ("adv", req.bits.tobytes(), req.status.tobytes())
                    )
        return stream

    assert run(force_py=False) == run(force_py=True)
