"""Multi-host helpers: single-process semantics on the 8-device CPU mesh.

True multi-process DCN rendezvous needs multiple hosts; what CI pins down
is the single-process contract every multi-host program degenerates to,
plus the mesh/slice arithmetic that is pure logic.
"""

import os

import jax
import pytest

from bevy_ggrs_tpu.parallel.multihost import (
    global_branch_mesh,
    initialize,
    local_branch_slice,
    process_topology,
)


def test_initialize_single_process_noop():
    assert initialize(num_processes=1) == (0, 1)


def test_global_branch_mesh_spans_all_devices():
    if len(jax.devices()) != 8:
        pytest.skip("needs the 8-device mesh (GGRS_TEST_TPU run on <8 chips)")
    mesh = global_branch_mesh(entity_shards=2)
    assert mesh.devices.size == len(jax.devices()) == 8
    assert mesh.axis_names == ("branch", "entity")
    assert mesh.devices.shape == (4, 2)


def test_local_branch_slice():
    # Single process owns the whole branch range (divisibility failures
    # need process_count > 1 and are covered by the arithmetic itself).
    assert local_branch_slice(64) == (0, 64)
    assert local_branch_slice(1) == (0, 1)


def test_process_topology_keys():
    topo = process_topology()
    assert topo["process_index"] == 0
    assert topo["process_count"] == 1
    assert topo["global_device_count"] == len(jax.devices())
    assert len(topo["local_devices"]) == len(jax.local_devices())


class TestTwoProcessDCN:
    """The demonstrated multihost path (round-2 weak #6): two real OS
    processes, 4 virtual CPU devices each, jax.distributed rendezvous at a
    TCP coordinator, one global [branch] mesh — a speculative rollout whose
    branch axis spans both processes, a cross-process confirmed-branch
    commit (the DCN collective), and a checksum allgather asserting both
    worlds are bitwise identical. See tests/multihost_worker.py."""

    @pytest.mark.skipif(
        jax.default_backend() == "cpu",
        reason="two-process jax.distributed rendezvous needs the cross-host "
        "collective transport the cpu-only jaxlib wheel does not ship; the "
        "single-process degeneracy above pins the semantics, and this path "
        "runs for real on TPU/GPU pods (GGRS_TEST_TPU)",
    )
    def test_two_process_rollout_and_commit(self):
        import socket
        import subprocess
        import sys as _sys

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()

        worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
        env = dict(os.environ)
        # The workers build their own backends (the coordinator needs two
        # fresh processes; this test process's 8-device CPU backend stays
        # untouched).
        env.pop("XLA_FLAGS", None)
        procs = [
            subprocess.Popen(
                [_sys.executable, worker, str(i), "2", str(port)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                env=env,
            )
            for i in range(2)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=240)
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        oks = []
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"worker {i} failed:\n{out[-3000:]}"
            lines = [l for l in out.splitlines() if l.startswith("MULTIHOST_OK")]
            assert lines, f"worker {i} printed no OK line:\n{out[-3000:]}"
            oks.append(lines[0].split())
        # Same checksums on both processes, for BOTH phases (the workers
        # also assert this internally via allgather — this is the
        # out-of-band double check).
        assert oks[0][2] == oks[1][2]
        assert oks[0][3] == oks[1][3]  # live=<hex> token, phase 2
