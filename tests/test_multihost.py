"""Multi-host helpers: single-process semantics on the 8-device CPU mesh.

True multi-process DCN rendezvous needs multiple hosts; what CI pins down
is the single-process contract every multi-host program degenerates to,
plus the mesh/slice arithmetic that is pure logic.
"""

import jax
import pytest

from bevy_ggrs_tpu.parallel.multihost import (
    global_branch_mesh,
    initialize,
    local_branch_slice,
    process_topology,
)


def test_initialize_single_process_noop():
    assert initialize(num_processes=1) == (0, 1)


def test_global_branch_mesh_spans_all_devices():
    if len(jax.devices()) != 8:
        pytest.skip("needs the 8-device mesh (GGRS_TEST_TPU run on <8 chips)")
    mesh = global_branch_mesh(entity_shards=2)
    assert mesh.devices.size == len(jax.devices()) == 8
    assert mesh.axis_names == ("branch", "entity")
    assert mesh.devices.shape == (4, 2)


def test_local_branch_slice():
    # Single process owns the whole branch range (divisibility failures
    # need process_count > 1 and are covered by the arithmetic itself).
    assert local_branch_slice(64) == (0, 64)
    assert local_branch_slice(1) == (0, 1)


def test_process_topology_keys():
    topo = process_topology()
    assert topo["process_index"] == 0
    assert topo["process_count"] == 1
    assert topo["global_device_count"] == len(jax.devices())
    assert len(topo["local_devices"]) == len(jax.local_devices())
