"""Session-level robustness against hostile datagrams.

`protocol.decode` fuzzing (test_protocol_fuzz) covers parse safety; this
covers SEMANTIC hostility: well-formed messages with malicious contents —
out-of-range handles, absurd frames, lying span lengths and acks,
checksum bombs.

Threat model (same as the reference's ggrs): the transport is
unauthenticated UDP. Datagrams from UNKNOWN addresses must be completely
inert. Datagrams spoofing a REAL peer's source address are
indistinguishable from that peer's own traffic — a full spoofer can forge
inputs or acks outright, which no unauthenticated protocol can survive
(runs needing that guarantee must wrap the transport in an authenticated
channel) — so for peer-spoofed garbage the guaranteed properties are: no
exception ever escapes, and the session object stays usable. One concrete
defense IS enforced and tested: a peer acking AHEAD of what it was ever
offered (lying or buggy) cannot trick us into trimming unsent input
history, which would otherwise stall the victim permanently.
"""

import numpy as np
import pytest

from bevy_ggrs_tpu.session import (
    EventKind,
    PredictionThreshold,
    SessionState,
    protocol as proto,
)
from bevy_ggrs_tpu.transport.loopback import LoopbackNetwork

from tests.test_p2p import (
    FPS_DT,
    common_confirmed_checksums,
    make_pair,
    scripted_input,
)

HOSTILE = [
    proto.InputMsg(handle=250, start_frame=0, payload=b"\x01" * 8, num=8,
                   ack_frame=0, sender_frame=0, advantage=0),
    proto.InputMsg(handle=0, start_frame=2**31 - 2, payload=b"\x02", num=1,
                   ack_frame=2**31 - 2, sender_frame=2**31 - 2, advantage=0),
    proto.InputMsg(handle=1, start_frame=-5000, payload=b"\x03" * 4, num=4,
                   ack_frame=-1, sender_frame=-1, advantage=-30000),
    # num lies about the payload size (unpacker must stop at the data).
    proto.InputMsg(handle=1, start_frame=5, payload=b"\x04", num=60000,
                   ack_frame=0, sender_frame=5, advantage=0),
    proto.InputAck(handle=200, ack_frame=2**31 - 1),
    proto.ChecksumReport(frame=2**30, checksum=0xDEADBEEF),
    proto.ChecksumReport(frame=-7, checksum=0),
    proto.QualityReport(send_time_ms=2**32 - 1, frame_advantage=-32768),
    proto.SyncReply(nonce=0x41414141),
]


def _drive(net, peers, n, hostile_from=None):
    events = []
    for i in range(n):
        net.advance(FPS_DT)
        if hostile_from is not None:
            for msg in HOSTILE[i % len(HOSTILE):][:2]:
                net._send(hostile_from, ("peer", 0), proto.encode(msg))
                net._send(hostile_from, ("peer", 1), proto.encode(msg))
        for session, runner in peers:
            session.poll_remote_clients()
            events.extend(session.events())
            if session.current_state() != SessionState.RUNNING:
                continue
            for h in session.local_player_handles():
                session.add_local_input(h, scripted_input(h, session.current_frame))
            try:
                requests = session.advance_frame()
            except PredictionThreshold:
                continue
            runner.handle_requests(requests, session)
    return events


def test_unknown_address_hostility_is_inert():
    """Garbage from a non-peer address: full progress, full agreement, no
    desync events — exactly as if the intruder didn't exist."""
    net = LoopbackNetwork(latency=1 * FPS_DT, seed=3)
    peers = make_pair(net)
    events = _drive(net, peers, 90, hostile_from=("intruder", 9))
    (sa, ra), (sb, rb) = peers
    assert ra.frame > 40 and rb.frame > 40
    frames, pairs = common_confirmed_checksums(peers)
    assert frames and all(a == b for a, b in pairs)
    assert not any(e.kind == EventKind.DESYNC_DETECTED for e in events)


def test_peer_spoofed_hostility_never_raises():
    """Source-spoofed garbage claiming to be a peer: the protocol cannot
    authenticate it away (threat-model note in the module docstring), but
    nothing may crash and the sessions must stay usable."""
    net = LoopbackNetwork(latency=1 * FPS_DT, seed=3)
    peers = make_pair(net)
    # Spoof as peer 1 toward both; everything must be absorbed silently.
    _drive(net, peers, 90, hostile_from=("peer", 1))
    for session, runner in peers:
        session.events()
        session.current_state()
        for h in session.remote_player_handles():
            session.network_stats(h)


def test_lying_ack_ahead_cannot_stall_the_victim():
    """A peer (or spoofer) acking frames never offered must not trim the
    victim's unsent history: the clamped ack keeps the genuine resend
    flowing and the pair progresses normally."""
    net = LoopbackNetwork(latency=1 * FPS_DT, seed=5)
    peers = make_pair(net)
    lying_ack = proto.InputAck(handle=0, ack_frame=2**31 - 1)
    lying_ack1 = proto.InputAck(handle=1, ack_frame=2**31 - 1)
    events = []
    for i in range(90):
        net.advance(FPS_DT)
        # Both peers constantly receive ack-ahead lies for every handle.
        net._send(("peer", 1), ("peer", 0), proto.encode(lying_ack))
        net._send(("peer", 1), ("peer", 0), proto.encode(lying_ack1))
        net._send(("peer", 0), ("peer", 1), proto.encode(lying_ack))
        net._send(("peer", 0), ("peer", 1), proto.encode(lying_ack1))
        for session, runner in peers:
            session.poll_remote_clients()
            events.extend(session.events())
            if session.current_state() != SessionState.RUNNING:
                continue
            for h in session.local_player_handles():
                session.add_local_input(h, scripted_input(h, session.current_frame))
            try:
                requests = session.advance_frame()
            except PredictionThreshold:
                continue
            runner.handle_requests(requests, session)
    (sa, ra), (sb, rb) = peers
    assert ra.frame > 40 and rb.frame > 40, "ack-ahead lie stalled the pair"
    frames, pairs = common_confirmed_checksums(peers)
    assert frames and all(a == b for a, b in pairs)


def test_version_skew_surfaces_instead_of_silent_stall():
    """A peer speaking a different protocol version is dropped datagram by
    datagram (no cross-version parse exists), but after a handful of them
    the session emits VERSION_MISMATCH so operators see the skew instead of
    an indefinite SYNCHRONIZING stall."""
    net = LoopbackNetwork(latency=1 * FPS_DT, seed=11)
    peers = make_pair(net)
    # Re-version a legitimate message: same magic, version+1.
    skewed = bytearray(proto.encode(proto.SyncRequest(nonce=1234)))
    assert skewed[1] == proto.VERSION
    skewed[1] = proto.VERSION + 1
    events = []
    for i in range(30):
        net.advance(FPS_DT)
        net._send(("peer", 1), ("peer", 0), bytes(skewed))
        for session, runner in peers:
            session.poll_remote_clients()
            events.extend(session.events())
    mismatches = [e for e in events if e.kind == EventKind.VERSION_MISMATCH]
    assert len(mismatches) == 1, "one event per skewed peer, not per datagram"
    assert mismatches[0].data["peer_version"] == proto.VERSION + 1
    assert mismatches[0].data["local_version"] == proto.VERSION
    assert mismatches[0].data["count"] >= 5
    # A plain-garbage datagram (wrong magic) must NOT count as skew.
    assert proto.version_mismatch(b"\x00" * 16) is None
