"""SyncTestSession end-to-end: the minimum slice of the survey's build plan
(§7 step 3) — box_game running under forced rollbacks with checksum
comparison every frame, driven through the real request protocol and the
fused device executor.

Reference behavior: `examples/box_game/box_game_synctest.rs:27-38` +
`src/ggrs_stage.rs:163-193`.
"""

import numpy as np
import pytest

from bevy_ggrs_tpu import checksum, combine64
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.runner import RollbackRunner
from bevy_ggrs_tpu.schedule import make_inputs
from bevy_ggrs_tpu.session import (
    InvalidRequest,
    MismatchedChecksum,
    SyncTestSession,
)
from bevy_ggrs_tpu.session.requests import AdvanceFrame, LoadGameState, SaveGameState


def make(num_players=2, check_distance=2, input_delay=0, max_prediction=8):
    session = SyncTestSession(
        num_players,
        box_game.INPUT_SPEC,
        check_distance=check_distance,
        max_prediction=max_prediction,
        input_delay=input_delay,
    )
    runner = RollbackRunner(
        box_game.make_schedule(),
        box_game.make_world(num_players).commit(),
        max_prediction=max_prediction,
        num_players=num_players,
        input_spec=box_game.INPUT_SPEC,
    )
    return session, runner


def tick(session, runner, bits):
    for h in range(session.num_players):
        session.add_local_input(h, bits[h])
    runner.handle_requests(session.advance_frame(), session)


def test_request_shape_before_and_after_check_distance():
    session, _ = make(check_distance=2)
    for h in range(2):
        session.add_local_input(h, np.uint8(0))
    reqs = session.advance_frame()
    # Frame 0: no history yet → plain [Save, Advance].
    assert [type(r) for r in reqs] == [SaveGameState, AdvanceFrame]
    for _ in range(2):
        for h in range(2):
            session.add_local_input(h, np.uint8(0))
        reqs = session.advance_frame()
    # Frame 2: forced rollback 2 deep → Save, Advance, Load(0), then 3
    # (Save, Advance) pairs replaying frames 0..2.
    kinds = [type(r) for r in reqs]
    assert kinds == [SaveGameState, AdvanceFrame, LoadGameState] + [
        SaveGameState, AdvanceFrame] * 3
    assert reqs[2].frame == 0


def test_synctest_deterministic_game_runs_clean():
    session, runner = make(num_players=2, check_distance=3)
    rng = np.random.RandomState(0)
    for _ in range(30):
        tick(session, runner, rng.randint(0, 16, size=2).astype(np.uint8))
    assert runner.frame == 30
    assert runner.rollbacks_total > 0  # forced rollbacks actually happened
    assert int(runner.state.resources["frame_count"]) == 30


def test_synctest_matches_straightline_simulation():
    """After N frames with rollbacks forced every frame, state must equal a
    straight single-pass simulation of the same inputs."""
    session, runner = make(num_players=2, check_distance=4)
    sched = box_game.make_schedule()
    oracle = box_game.make_world(2).commit()
    rng = np.random.RandomState(1)
    for _ in range(20):
        bits = rng.randint(0, 16, size=2).astype(np.uint8)
        tick(session, runner, bits)
        oracle = sched(oracle, make_inputs(bits))
    assert combine64(checksum(runner.state)) == combine64(checksum(oracle))


def test_synctest_detects_nondeterminism():
    """State mutated outside the rollback domain (bypassing the snapshot
    ring) must trip MismatchedChecksum on a later resimulation — the desync
    class the harness exists to catch (reference
    `examples/README.md:13-18`)."""
    session, runner = make(num_players=2, check_distance=2)
    tick(session, runner, np.zeros(2, np.uint8))
    # Out-of-band tamper: live state drifts, ring snapshots don't know.
    runner.state = runner.state.replace(
        components={
            **runner.state.components,
            "translation": runner.state.components["translation"] + 0.001,
        }
    )
    with pytest.raises(MismatchedChecksum):
        for _ in range(5):
            tick(session, runner, np.zeros(2, np.uint8))


def test_input_delay_shifts_effect():
    """With input_delay=2, an input issued at frame f takes effect at f+2
    (`with_input_delay`, box_game_p2p.rs:37)."""
    session, runner = make(num_players=1, check_distance=0, input_delay=2)
    tick(session, runner, np.array([box_game.INPUT_RIGHT], np.uint8))
    v_after_f0 = runner.world()["components"]["velocity"][0]
    assert v_after_f0[0] == 0.0  # delayed input not yet in effect
    tick(session, runner, np.zeros(1, np.uint8))
    tick(session, runner, np.zeros(1, np.uint8))
    v_after_f2 = runner.world()["components"]["velocity"][0]
    assert v_after_f2[0] > 0.0  # now it landed


def test_missing_input_rejected():
    session, _ = make(num_players=2)
    session.add_local_input(0, np.uint8(0))
    with pytest.raises(InvalidRequest):
        session.advance_frame()


def test_check_distance_beyond_prediction_rejected():
    with pytest.raises(InvalidRequest):
        SyncTestSession(2, box_game.INPUT_SPEC, check_distance=9, max_prediction=8)


def test_deep_prediction_window():
    """The temporal axis at 4x the reference's example depth: a 32-frame
    prediction window with 30-deep forced rollbacks every frame (the
    'long-context' analog, survey §5 — the frame axis is a lax.scan, so
    depth costs compile-time shape only, not host round trips)."""
    session, runner = make(check_distance=30, max_prediction=32)
    sched = box_game.make_schedule()
    oracle = box_game.make_world(2).commit()
    for i in range(40):
        bits = np.asarray([(i + h) % 16 for h in range(2)], np.uint8)
        tick(session, runner, bits)
        oracle = sched(oracle, make_inputs(bits))
    assert runner.frame == 40
    assert runner.rollback_frames_total >= 30 * 9  # deep resims really ran
    # And the deeply-resimulated state equals straight-line simulation.
    assert combine64(checksum(runner.state)) == combine64(checksum(oracle))
