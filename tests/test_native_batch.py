"""Batched-native data plane: bitwise parity vs the per-slot Python path.

The :class:`~bevy_ggrs_tpu.native.spec.NativeBatchPlane` consolidates the
whole per-slot host loop — as-used log appends, in-flight tree matches,
predictor window gathers, branch-tree builds and no-op tree re-use —
into two C calls per dispatch (``serve/batch.py::_dispatch_native``).
The committed device state is a function of the arrays these calls
produce, so the plane must be BITWISE identical to the per-slot path it
replaces (`_dispatch_python`, the ``GGRS_NO_NATIVE=1`` route): same jit
argument tensors, same branch trees, same predictor windows, same
committed state/rings — across heterogeneous rollback depths, predictor
ON and OFF, and admit/retire churn (which must also never recompile).

The in-process A/B here pins ``_plane = None`` on one core, which is
exactly the router's ``GGRS_NO_NATIVE=1`` fallback; CI additionally runs
this whole file under ``GGRS_NO_NATIVE=1`` so the pure-Python leg stays
exercised end to end.

Also covered: the MatchServer slot-template pool — a template-admitted
match must be indistinguishable (bitwise) from a cold-admitted one.
"""

import numpy as np
import pytest

from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.native import core as ncore
from bevy_ggrs_tpu.serve.batch import BatchedSessionCore
from bevy_ggrs_tpu.serve.server import MatchServer
from bevy_ggrs_tpu.session.builder import SessionBuilder
from bevy_ggrs_tpu.state import checksum, combine64
from bevy_ggrs_tpu.utils import xla_cache
from tests.test_batched_sessions import drive, make_script

P = 2
MAXPRED = 4
BRANCHES = 8
SPEC_FRAMES = 3

native = pytest.mark.skipif(
    not ncore.available(), reason="native session core did not build"
)


def make_core(num_slots=4, plane=True, **kw):
    core = BatchedSessionCore(
        box_game.make_schedule(), box_game.make_world(P).commit(),
        MAXPRED, P, box_game.INPUT_SPEC, num_slots=num_slots,
        num_branches=BRANCHES, spec_frames=SPEC_FRAMES, **kw,
    )
    if not plane:
        # Exactly the GGRS_NO_NATIVE=1 router fallback: _dispatch routes
        # to _dispatch_python when the plane is absent.
        core._plane = None
    core.warmup()
    return core


def capture_jit_args(core):
    """Record a deep copy of every dispatch's 15 jit argument arrays —
    the complete host->device contract (branch selectors, absorb
    metadata, staged bits/statuses, phase masks, branch trees)."""
    captured = []
    orig = core._finish_dispatch

    def wrapper(jit_args, post, reports):
        captured.append(tuple(np.array(a, copy=True) for a in jit_args))
        return orig(jit_args, post, reports)

    core._finish_dispatch = wrapper
    return captured


def assert_cores_bitwise_equal(nat, py, cap_n, cap_p):
    assert len(cap_n) == len(cap_p) > 0
    for d, (an, ap) in enumerate(zip(cap_n, cap_p)):
        for j, (x, y) in enumerate(zip(an, ap)):
            assert np.array_equal(x, y), (
                f"dispatch {d}: jit arg {j} diverges"
            )
    for s in nat.slots:
        assert s.frame == py.slots[s.index].frame
        if s.active:
            assert combine64(checksum(nat.slot_state(s.index))) == combine64(
                checksum(py.slot_state(s.index))
            )
    assert np.array_equal(
        np.asarray(nat.rings.frames), np.asarray(py.rings.frames)
    )
    assert np.array_equal(
        np.asarray(nat.rings.checksums), np.asarray(py.rings.checksums)
    )
    assert (nat.spec_hits, nat.spec_partial_hits, nat.spec_misses) == (
        py.spec_hits, py.spec_partial_hits, py.spec_misses
    )


def heterogeneous_scripts(rng, slots, cycles=3):
    """Distinct seed AND rollback depth per slot, plus one slot with a
    shorter script so the no-op lane (tree re-use copy path) runs."""
    scripts = {}
    for k, s in enumerate(slots):
        depth = 1 + (k % MAXPRED)
        c = cycles - 1 if k == len(slots) - 1 else cycles
        scripts[s] = make_script(
            seed=int(rng.randint(1 << 30)), depth=depth, cycles=c
        )
    return scripts


@native
@pytest.mark.parametrize("trial", [0, 1])
def test_parity_predictor_off(trial):
    """Property-based A/B: randomized heterogeneous-depth scripts through
    the plane vs the per-slot path — every jit argument tensor (including
    the [S,B,F] branch trees) and all committed state bitwise equal."""
    from bevy_ggrs_tpu.utils.metrics import Metrics

    rng = np.random.RandomState(1000 + trial)
    mn, mp = Metrics(), Metrics()
    nat = make_core(plane=True, predictor=False, metrics=mn)
    py = make_core(plane=False, predictor=False, metrics=mp)
    assert nat._plane is not None and py._plane is None
    cap_n, cap_p = capture_jit_args(nat), capture_jit_args(py)
    slots = [nat.admit() for _ in range(4)]
    for _ in range(4):
        py.admit()
    scripts = heterogeneous_scripts(rng, slots)
    drive(nat, scripts)
    drive(py, scripts)
    assert_cores_bitwise_equal(nat, py, cap_n, cap_p)
    assert nat.native_batch_calls > 0
    assert py.native_batch_calls == 0
    assert nat.native_batch_ms_total > 0.0
    # Satellite counters: the consolidated call is attributable.
    assert mn.counters["native_batch_calls"] == nat.native_batch_calls
    assert len(mn.series["native_batch_ms"]) > 0
    assert "native_batch_calls" not in mp.counters
    # The host-work decomposition stays a real measured split on BOTH
    # paths (not a dead column): the build sub-span is the batched build
    # call's wall time, arg assembly the rest of the staging loop.
    for m in (mn, mp):
        assert len(m.series["serve_branch_build"]) > 0
        assert len(m.series["serve_arg_assembly"]) > 0
    assert sum(mn.series["serve_branch_build"]) > 0.0


@native
def test_parity_predictor_on_trees_and_windows():
    """Predictor ON: the plane's batched window gather + seed staging
    must reproduce the Python path's per-slot
    ``predictor.window_indices`` + ``render_seed`` route bitwise — any
    divergence flips candidate order and shows up in the seeded branch
    trees the jit args carry."""
    rng = np.random.RandomState(77)
    nat = make_core(plane=True, predictor=True)
    if nat._predictor is None:
        pytest.skip("default predictor artifact does not bind box_game")
    py = make_core(plane=False, predictor=True)
    assert nat._plane is not None and py._plane is None
    cap_n, cap_p = capture_jit_args(nat), capture_jit_args(py)
    slots = [nat.admit() for _ in range(4)]
    for _ in range(4):
        py.admit()
    scripts = heterogeneous_scripts(rng, slots)
    drive(nat, scripts)
    drive(py, scripts)
    assert_cores_bitwise_equal(nat, py, cap_n, cap_p)
    assert nat.predictor_rank_dispatches > 0
    assert py.predictor_rank_dispatches > 0
    # Direct window check: the last dispatch's gathered [W, P] universe
    # indices for every ranked slot must equal the Python oracle
    # recomputed from the same log at the same anchor.
    plane = nat._plane
    checked = 0
    for s in nat.slots:
        if not s.active or not plane.win_mask[s.index]:
            continue
        want = nat._predictor.window_indices(
            s.input_log, int(plane.win_anchors[s.index]), P
        )
        assert np.array_equal(plane.wins[s.index], want), s.index
        checked += 1
    assert checked > 0


@native
def test_churn_zero_recompiles_on_plane():
    """Admit/retire churn through the batched-native dispatch leaves the
    backend-compile counter and the executor cache untouched — the plane
    stages into persistent [S, ...] SoA buffers and fresh-per-dispatch
    jit args, never shape-specialized per occupancy."""
    assert xla_cache.install_compile_listeners()
    core = make_core(plane=True, predictor=False)
    s = core.admit()
    drive(core, {s: make_script(seed=1, depth=2, cycles=1)})
    calls0 = core.native_batch_calls
    cache0 = core._exec.cache_size()
    base = xla_cache.compile_counters()["backend_compiles"]
    for k in range(3):
        core.retire(s)
        s = core.admit()
        s2 = core.admit()
        drive(core, {
            s: make_script(seed=40 + k, depth=1 + k, cycles=1),
            s2: make_script(seed=50 + k, depth=2, cycles=1),
        })
        core.retire(s2)
    assert xla_cache.compile_counters()["backend_compiles"] == base
    assert core._exec.cache_size() == cache0 == 1
    assert core.native_batch_calls > calls0


# ---------------------------------------------------------------------------
# Slot template pool: pre-warmed admission is bitwise-invisible
# ---------------------------------------------------------------------------


def _make_server():
    srv = MatchServer(
        box_game.make_schedule(), box_game.make_world(P).commit(),
        MAXPRED, P, box_game.INPUT_SPEC,
        capacity=2, stagger_groups=1, num_branches=BRANCHES,
        spec_frames=SPEC_FRAMES,
    )
    srv.warmup()
    return srv


def _make_session():
    return (
        SessionBuilder(box_game.INPUT_SPEC)
        .with_num_players(P)
        .with_max_prediction_window(MAXPRED)
        .with_check_distance(2)
        .start_synctest_session()
    )


def _inputs_for(seed):
    def f(frame, handle):
        return np.uint8((frame * 3 + handle * 5 + seed) % 16)

    return f


def test_template_pool_is_codec_identity():
    """The pool's decoded state must be flat-byte identical to the live
    template, and its ring identical to a cold ``ring_init`` — the
    witness that template admission cannot perturb anything."""
    import jax

    from bevy_ggrs_tpu.state import ring_init

    srv = _make_server()
    assert srv._slot_templates
    tpl_ring, tpl_state = srv._slot_templates[0]
    core = srv.groups[0]
    for x, y in zip(
        jax.tree_util.tree_leaves(tpl_state),
        jax.tree_util.tree_leaves(core._template),
    ):
        assert np.array_equal(np.asarray(x), np.asarray(y))
    cold = ring_init(core._template, core.ring_depth)
    assert np.array_equal(
        np.asarray(tpl_ring.frames), np.asarray(cold.frames)
    )
    assert np.array_equal(
        np.asarray(tpl_ring.checksums), np.asarray(cold.checksums)
    )
    for x, y in zip(
        jax.tree_util.tree_leaves(tpl_ring.states),
        jax.tree_util.tree_leaves(cold.states),
    ):
        assert np.array_equal(np.asarray(x), np.asarray(y))


def test_template_admission_bitwise_continuity():
    """A match admitted through the pre-warmed template pool must run
    bitwise identical to one cold-admitted on a pool-less server: same
    per-frame state checksums, same ring contents, zero desyncs (the
    synctest sessions self-verify every frame)."""
    warm, cold = _make_server(), _make_server()
    assert warm._slot_templates
    cold._slot_templates = []  # force the per-joiner ring_init path
    hw = warm.add_match(_make_session(), _inputs_for(3))
    hc = cold.add_match(_make_session(), _inputs_for(3))
    assert warm.templates_admitted == 1
    assert cold.templates_admitted == 0
    for _ in range(20):
        warm.run_frame()
        cold.run_frame()
    cw, cc = warm.groups[hw.group], cold.groups[hc.group]
    assert cw.slots[hw.slot].frame == cc.slots[hc.slot].frame == 20
    assert combine64(checksum(cw.slot_state(hw.slot))) == combine64(
        checksum(cc.slot_state(hc.slot))
    )
    assert np.array_equal(
        np.asarray(cw.rings.frames)[hw.slot],
        np.asarray(cc.rings.frames)[hc.slot],
    )
    assert np.array_equal(
        np.asarray(cw.rings.checksums)[hw.slot],
        np.asarray(cc.rings.checksums)[hc.slot],
    )
    # Queued admissions ride the template pool too (the recycled entry
    # means churn never drains it) — and a pooled admission drains at
    # the TOP of the frame, so it ticks on the very frame that drains
    # it (5 run_frames -> frame 5, not 4).
    warm.retire_match(hw)
    h2 = warm.enqueue_match(_make_session(), _inputs_for(5))
    warm.run_frame()
    assert warm.templates_admitted == 2
    assert len(warm._slot_templates) == warm.admit_budget * len(warm.groups)
    for _ in range(4):
        warm.run_frame()
    assert warm.groups[h2.group].slots[h2.slot].frame == 5
