"""Speculation-safety attestation: the per-model bitwise claim, machine-checked.

Speculative recovery reuses states computed by a DIFFERENT XLA executable
(the vmapped rollout) than the serial burst — sound only when both round
every float op identically (docs/determinism.md). Round 2 left that as a
docstring claim per model; this suite exercises the round-3 mechanism:
``attest_speculation_safety`` runs both executables on identical inputs at
their real shapes and compares checksum streams bitwise, and the runner
auto-disables speculation (with an app-visible event) on mismatch.

Also covers the branch-values plumbing that made projectiles speculation
real: ``InputSpec.values`` (0..31, FIRE enumerable) flows through
``GGRSPlugin.with_speculation`` into the structured branch tree, and a
fire-press misprediction is recovered as a speculative hit.
"""

import numpy as np
import pytest

from bevy_ggrs_tpu.models import boids, box_game, neural_bots
from bevy_ggrs_tpu.models import projectiles as pj
from bevy_ggrs_tpu.schedule import PREDICTED, Schedule
from bevy_ggrs_tpu.session.common import EventKind
from bevy_ggrs_tpu.spec_runner import (
    SpeculativeRollbackRunner,
    attest_speculation_safety,
)

from tests.test_spec_runner import (
    ChecksumLog,
    rollback_requests,
    step_requests,
)


def make_spec_runner(model, world, num_branches=8, spec_frames=4, **kw):
    return SpeculativeRollbackRunner(
        model.make_schedule(),
        world.commit(),
        max_prediction=8,
        num_players=2,
        input_spec=model.INPUT_SPEC,
        num_branches=num_branches,
        spec_frames=spec_frames,
        **kw,
    )


class TestAttestation:
    def test_box_game_attests_safe(self):
        runner = make_spec_runner(box_game, box_game.make_world(2))
        report = attest_speculation_safety(runner)
        assert report.ok and report.branches_checked >= 1
        assert report.frames == 4

    def test_projectiles_attests_safe(self):
        """Backs the models/projectiles.py docstring claim: spawn/despawn
        scatters under vmap agree bitwise with the serial burst."""
        runner = make_spec_runner(pj, pj.make_world(2, capacity=16))
        report = attest_speculation_safety(runner)
        assert report.ok
        # The random inputs drawn from INPUT_SPEC.values (0..31) include
        # FIRE bits, so the attested trajectories really exercised
        # in-step spawn/despawn — check the value universe is the wide one.
        assert max(runner._branch_values) == 31

    def test_neural_bots_reject_or_pass(self):
        """Float-matmul model: vmapping the MLP over branches turns
        [cap, OBS] @ [OBS, H] into a batched matmul, which backends may
        accumulate in a different order — empirically the CPU backend DOES
        round differently (attestation caught it at the first advanced
        frame), which was believed safe until this check existed. The
        contract is therefore reject-or-pass: a truthful verdict wired into
        auto-disable, same as boids."""
        runner = SpeculativeRollbackRunner(
            neural_bots.make_schedule(),
            neural_bots.make_world(32, 2).commit(),
            max_prediction=8,
            num_players=2,
            input_spec=neural_bots.INPUT_SPEC,
            num_branches=4,
            spec_frames=4,
        )
        runner.warmup()
        report = runner.attestation
        assert report is not None
        assert runner.speculation_enabled == report.ok
        if not report.ok:
            runner.speculate(0)
            assert runner._result is None

    def test_boids_reject_or_pass(self):
        """Float-reduction model: vmapped-vs-serial agreement is platform
        dependent, so the contract is only that attestation returns a
        truthful verdict and warmup wires a False verdict into auto-disable."""
        runner = SpeculativeRollbackRunner(
            boids.make_schedule(),
            boids.make_world(64, 2).commit(),
            max_prediction=8,
            num_players=2,
            input_spec=boids.INPUT_SPEC,
            num_branches=4,
            spec_frames=4,
        )
        runner.warmup()
        report = runner.attestation
        assert report is not None
        assert runner.speculation_enabled == report.ok
        if not report.ok:
            runner.speculate(0)  # must be a no-op, not a crash
            assert runner._result is None

    def test_report_covers_all_branches_and_structured_tree(self):
        """Round-3 verdict weak #3: attestation must exercise every branch
        (scanned serial executable, not 8 Python re-runs) and the
        structured tree's real pinned-prefix branch tensors."""
        runner = make_spec_runner(box_game, box_game.make_world(2))
        report = attest_speculation_safety(runner)
        assert report.ok
        assert report.branches_checked >= 1  # real-executable spot check
        assert report.scanned_branches == runner.num_branches
        assert report.structured_checked

    def test_meshed_runner_attestation_exercises_sharded_executables(self):
        """A meshed SpeculativeRollbackRunner's attestation runs the
        SHARDED rollout and serial executables (third/fourth XLA programs
        the unsharded attestation never sees) — round-3 verdict weak #3c."""
        import jax
        from jax.sharding import Mesh

        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device CPU test mesh")
        mesh = Mesh(
            np.array(jax.devices()[:8]).reshape(2, 4), ("branch", "entity")
        )
        runner = SpeculativeRollbackRunner(
            box_game.make_schedule(),
            box_game.make_world(2, capacity=8).commit(),
            max_prediction=8,
            num_players=2,
            input_spec=box_game.INPUT_SPEC,
            num_branches=4,
            spec_frames=4,
            mesh=mesh,
        )
        runner.warmup()
        report = runner.attestation
        assert report is not None and report.ok
        assert report.scanned_branches == 4
        assert report.structured_checked
        assert runner.speculation_enabled

    def test_status_reading_model_is_caught_and_disabled(self):
        """A system that reads PlayerInputs.status into state is the
        documented speculation-unsafe shape (speculative rollouts run
        all-PREDICTED; a real recovery burst runs CONFIRMED). Attestation
        must catch it and warmup must auto-disable speculation."""

        def status_leak_system(state, inputs):
            leak = jnp_sum_status(inputs)
            return state.replace(
                resources={
                    **state.resources,
                    "frame_count": state.resources["frame_count"] + leak,
                }
            )

        def jnp_sum_status(inputs):
            import jax.numpy as jnp

            return jnp.sum(inputs.status).astype(jnp.uint32)

        world = box_game.make_world(2)
        runner = SpeculativeRollbackRunner(
            Schedule([box_game.move_cube_system, status_leak_system]),
            world.commit(),
            max_prediction=8,
            num_players=2,
            input_spec=box_game.INPUT_SPEC,
            num_branches=4,
            spec_frames=4,
        )
        runner.warmup()
        assert runner.attestation is not None and not runner.attestation.ok
        assert runner.attestation.mismatch_branch is not None
        assert not runner.speculation_enabled
        runner.speculate(0)
        assert runner._result is None

    def test_app_surfaces_disable_event(self):
        """GGRSPlugin.build wires a failed attestation into an app-visible
        SPECULATION_DISABLED event (round-2 verdict: auto-disable + event)."""
        import jax.numpy as jnp

        from bevy_ggrs_tpu.app import GGRSPlugin

        def status_leak(state, inputs):
            return state.replace(
                resources={
                    **state.resources,
                    "frame_count": state.resources["frame_count"]
                    + jnp.sum(inputs.status).astype(jnp.uint32),
                }
            )

        def setup(world, app):
            box_game.spawn_players(
                world, 2, next_id=app.rollback_id_provider.next_id
            )

        plugin = (
            GGRSPlugin(box_game.INPUT_SPEC)
            .with_num_players(2)
            .register_rollback_component(
                "translation", shape=(3,), dtype=jnp.float32
            )
            .register_rollback_component(
                "velocity", shape=(3,), dtype=jnp.float32
            )
            .register_rollback_component(
                "player_handle", dtype=jnp.int32, default=-1
            )
            .register_rollback_resource("frame_count", jnp.uint32(0))
            .with_rollback_schedule(
                Schedule([box_game.move_cube_system, status_leak])
            )
            .with_input_system(lambda h, app: np.uint8(0))
            .with_setup_system(setup)
            .with_speculation(4)
        )
        app = plugin.build()
        kinds = [e.kind for e in app.events]
        assert EventKind.SPECULATION_DISABLED in kinds
        assert not app.stage.runner.speculation_enabled


class TestAttestationCache:
    """The process-level attestation memo (round-3 verdict weak #6): the
    verdict is a property of the two XLA executables — schedule, shapes,
    geometry, backend — so constructing a second runner of the same model
    must reuse it instead of re-running both executables."""

    def _fresh(self, monkeypatch, counter):
        import bevy_ggrs_tpu.spec_runner as sr

        monkeypatch.setattr(sr, "_ATTEST_MEMO", {})
        real = sr.attest_speculation_safety

        def counting(runner, **kw):
            counter.append(runner)
            return real(runner, **kw)

        monkeypatch.setattr(sr, "attest_speculation_safety", counting)

    def test_same_model_same_shape_attests_once(self, monkeypatch):
        calls = []
        self._fresh(monkeypatch, calls)
        for _ in range(2):
            runner = make_spec_runner(box_game, box_game.make_world(2))
            runner.warmup()
            assert runner.attestation is not None and runner.attestation.ok
        assert len(calls) == 1

    def test_different_shape_attests_fresh(self, monkeypatch):
        calls = []
        self._fresh(monkeypatch, calls)
        r1 = make_spec_runner(box_game, box_game.make_world(2))
        r1.warmup()
        r2 = make_spec_runner(
            box_game, box_game.make_world(2), num_branches=16
        )
        r2.warmup()
        assert len(calls) == 2

    def test_different_schedule_closure_attests_fresh(self, monkeypatch):
        """Two schedules from the same factory share bytecode; the
        fingerprint must still split them by what the closures capture."""
        calls = []
        self._fresh(monkeypatch, calls)
        for kernel in ("xla", "pallas"):
            runner = SpeculativeRollbackRunner(
                boids.make_schedule(kernel=kernel),
                boids.make_world(32, 2).commit(),
                max_prediction=8,
                num_players=2,
                input_spec=boids.INPUT_SPEC,
                num_branches=4,
                spec_frames=4,
            )
            runner.warmup()
        assert len(calls) == 2

    def test_env_var_disables_cache(self, monkeypatch):
        calls = []
        self._fresh(monkeypatch, calls)
        monkeypatch.setenv("GGRS_ATTEST_CACHE", "0")
        for _ in range(2):
            runner = make_spec_runner(box_game, box_game.make_world(2))
            runner.warmup()
        assert len(calls) == 2


class TestProjectilesSpeculation:
    """The round-2 hole: GGRSStage built the runner with default
    branch_values=range(16), so a FIRE (1<<4) press could never be a
    speculative hit. Now the value set derives from InputSpec.values."""

    def test_plugin_derives_branch_values_from_input_spec(self):
        from bevy_ggrs_tpu.app import GGRSPlugin

        def setup(host, app):
            pass  # world built by with_setup_system is optional here

        plugin = (
            GGRSPlugin(pj.INPUT_SPEC)
            .with_num_players(2)
            .with_world_capacity(16)
            .with_rollback_schedule(pj.make_schedule())
            .with_input_system(lambda h, app: np.uint8(0))
            .with_speculation(8)
        )
        # Seed the registry so the default HostWorld matches the model.
        plugin.registry = pj.make_registry()
        app = plugin.build()
        assert list(app.stage.runner._branch_values) == list(range(32))

    def test_fire_press_misprediction_is_a_spec_hit(self):
        """One player presses FIRE at the speculation anchor; the structured
        tree (values 0..31) enumerates that change, so the rollback burst
        commits a precomputed branch instead of resimulating."""
        serial = _projectiles_serial()
        spec = make_spec_runner(
            pj, pj.make_world(2, capacity=16), num_branches=96, spec_frames=4
        )
        assert 16 in spec._branch_values  # FIRE reachable

        fire = np.uint8(pj.INPUT_FIRE)
        logs = (ChecksumLog(), ChecksumLog())
        # Frames 0..2 advance normally (all-zero inputs, confirmed).
        for f in range(3):
            reqs = step_requests(f, [0, 0])
            serial.handle_requests(reqs, logs[0])
            spec.handle_requests(reqs, logs[1])
        # Speculate from confirmed frame 2 (anchor 3), no session pinning.
        spec.speculate(2)
        # Frames 3, 4 advance on the repeat-last prediction (no fire)...
        for f in (3, 4):
            reqs = step_requests(f, [0, 0])
            serial.handle_requests(reqs, logs[0])
            spec.handle_requests(reqs, logs[1])
        # ...but player 1 actually pressed FIRE at frame 3 and held it.
        corrected = [[0, fire], [0, fire]]
        reqs = rollback_requests(3, corrected)
        serial.handle_requests(reqs, logs[0])
        spec.handle_requests(reqs, logs[1])

        assert spec.spec_hits == 1 and spec.spec_misses == 0
        assert serial.frame == spec.frame
        assert logs[0].seen == logs[1].seen  # bitwise checksum agreement
        # The committed world really contains player 1's projectile.
        from bevy_ggrs_tpu.state import to_host

        h = to_host(spec.state)
        is_proj = h["alive"] & (h["components"]["kind"] == pj.KIND_PROJECTILE)
        assert is_proj.any()
        assert (h["components"]["owner"][is_proj] == 1).all()

    def test_default_values_could_never_hit_fire(self):
        """Control: with the round-2 default tree (0..15) the same script is
        a guaranteed miss — demonstrating the bug this round fixed."""
        spec = make_spec_runner(
            pj,
            pj.make_world(2, capacity=16),
            num_branches=96,
            spec_frames=4,
            branch_values=range(16),
        )
        logs = ChecksumLog()
        for f in range(3):
            spec.handle_requests(step_requests(f, [0, 0]), logs)
        spec.speculate(2)
        for f in (3, 4):
            spec.handle_requests(step_requests(f, [0, 0]), logs)
        fire = np.uint8(pj.INPUT_FIRE)
        spec.handle_requests(
            rollback_requests(3, [[0, fire], [0, fire]]), logs
        )
        assert spec.spec_hits == 0 and spec.spec_misses == 1


def _projectiles_serial():
    from bevy_ggrs_tpu.runner import RollbackRunner

    return RollbackRunner(
        pj.make_schedule(),
        pj.make_world(2, capacity=16).commit(),
        max_prediction=8,
        num_players=2,
        input_spec=pj.INPUT_SPEC,
    )


class TestExhaustiveAndDegradation:
    def test_exhaustive_mode_real_checks_every_branch(self, monkeypatch):
        """GGRS_ATTEST_EXHAUSTIVE=1: every branch of BOTH tensors replays
        through the real serial executable (2B total), independent of the
        scanned proxy's verdict."""
        monkeypatch.setenv("GGRS_ATTEST_EXHAUSTIVE", "1")
        runner = make_spec_runner(box_game, box_game.make_world(2))
        report = attest_speculation_safety(runner)
        assert report.ok and report.exhaustive
        assert report.branches_checked == runner.num_branches
        assert report.real_checked == 2 * runner.num_branches

    def test_exhaustive_verdict_not_served_from_standard_cache(
        self, monkeypatch
    ):
        """The memo key includes the exhaustive flag: a standard cached
        verdict must not satisfy an exhaustive request."""
        import bevy_ggrs_tpu.spec_runner as sr

        monkeypatch.delenv("GGRS_ATTEST_EXHAUSTIVE", raising=False)
        a = make_spec_runner(box_game, box_game.make_world(2))
        ka = sr._attestation_key(a)
        monkeypatch.setenv("GGRS_ATTEST_EXHAUSTIVE", "1")
        kb = sr._attestation_key(a)
        assert ka is not None and kb is not None and ka != kb

    def test_proxy_divergence_surfaces_degradation_event(self, monkeypatch):
        """When attestation passes but the scanned proxy self-disqualifies,
        the app must surface ATTESTATION_DEGRADED with the report attached
        (round-4 verdict weak #7) — forced here by faking the report."""
        import bevy_ggrs_tpu.spec_runner as sr
        from bevy_ggrs_tpu.app import GGRSPlugin

        degraded = sr.AttestationReport(
            ok=True, branches_checked=8, frames=4, scanned_branches=8,
            structured_checked=True, scanned_proxy_divergence=True,
            real_checked=10,
        )
        monkeypatch.setattr(
            sr, "attest_speculation_safety", lambda r, **kw: degraded
        )
        monkeypatch.setenv("GGRS_ATTEST_CACHE", "0")
        def setup(world, app):
            box_game.spawn_players(
                world, 2, next_id=app.rollback_id_provider.next_id
            )

        plugin = (
            GGRSPlugin(box_game.INPUT_SPEC)
            .with_num_players(2)
            .with_rollback_schedule(box_game.make_schedule())
            .with_input_system(lambda h, app: np.uint8(0))
            .with_setup_system(setup)
            .with_speculation(8)
        )
        plugin.registry = box_game.make_registry()
        app = plugin.build()
        kinds = [e.kind for e in app.events]
        assert EventKind.ATTESTATION_DEGRADED in kinds
        assert EventKind.SPECULATION_DISABLED not in kinds
        ev = next(
            e for e in app.events
            if e.kind == EventKind.ATTESTATION_DEGRADED
        )
        assert ev.data["scanned_proxy_divergence"] is True
        assert ev.data["real_checked"] == 10
        # Speculation itself stays ENABLED: degraded coverage is a
        # warning, not a failure.
        assert app.stage.runner.speculation_enabled
