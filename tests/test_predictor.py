"""Learned input prediction (predict/): the determinism contract.

Four layers, each with its own witness:

- **Artifact** — canonical bytes (no container metadata), a content hash
  stable across saves, processes, and platforms, and typed refusal of
  foreign/truncated/trailing bytes.
- **Handshake** — the resolved predictor's content hash is the session
  config digest; a digest-mismatched peer pair never synchronizes and
  surfaces one typed ``CONFIG_MISMATCH`` event per endpoint (never a
  desync).
- **Trees** — predictor-seeded branch trees are bitwise identical
  between the native C++ builder and the pure-Python fallback, keep
  branch 0 repeat-last, and change the dedup signature; the batched
  session-axis ranker matches the host rollout element-for-element.
- **Sessions** — a predictor-ON peer pair is wire-bitwise invisible
  (identical non-handshake datagrams and confirmed checksums vs the
  predictor-OFF run of the same script), and predictor OFF is bitwise
  identical to an unconfigured runner.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.native import core as ncore
from bevy_ggrs_tpu.native import spec as native_spec
from bevy_ggrs_tpu.predict import (
    DEFAULT_ARTIFACT,
    InputPredictor,
    PredictorWeights,
    load_artifact,
    load_default,
    resolve_predictor,
    resolve_predictor_config,
    save_artifact,
)
from bevy_ggrs_tpu.schedule import InputSpec
from bevy_ggrs_tpu.session import (
    EventKind,
    PlayerType,
    PredictionThreshold,
    SessionBuilder,
    SessionState,
)
from bevy_ggrs_tpu.session import protocol as proto
from bevy_ggrs_tpu.spec_runner import SpeculativeRollbackRunner
from bevy_ggrs_tpu.transport.loopback import LoopbackNetwork

from tests.test_p2p import (
    FPS_DT,
    common_confirmed_checksums,
    scripted_input,
)

UNIVERSE = list(range(16))
MAXPRED = 8


# --------------------------------------------------------------------------
# Artifact determinism
# --------------------------------------------------------------------------


class TestArtifact:
    def test_canonical_bytes_roundtrip(self, tmp_path):
        w = load_default()
        data = w.to_bytes()
        # Committed artifact == canonical bytes of its own weights: the
        # file carries nothing (timestamps, container metadata) beyond
        # the canonical string.
        with open(DEFAULT_ARTIFACT, "rb") as f:
            assert f.read() == data
        # save -> load -> save is byte-stable.
        p1, p2 = str(tmp_path / "a.ggrspred"), str(tmp_path / "b.ggrspred")
        save_artifact(w, p1)
        save_artifact(load_artifact(p1), p2)
        with open(p1, "rb") as f1, open(p2, "rb") as f2:
            assert f1.read() == f2.read() == data

    def test_content_hash_stable_across_processes(self):
        """The wire digest must not depend on process state (hash
        randomization, import order, caches) — re-derive it in a fresh
        interpreter and compare."""
        env = dict(os.environ)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        out = subprocess.run(
            [sys.executable, "-c",
             "from bevy_ggrs_tpu.predict import load_default;"
             "print(load_default().content_hash)"],
            capture_output=True, text=True, env=env, check=True,
        )
        assert int(out.stdout.strip()) == load_default().content_hash

    def test_hash_tracks_weight_bytes(self, tmp_path):
        w = load_default()
        w1 = np.array(w.w1, copy=True)
        w1[0, 0] = np.int8(int(w1[0, 0]) ^ 1)
        perturbed = PredictorWeights(
            w.weight_version, w.window, w.value_slots, w.phase_mod,
            w.hidden, w.shift, w1, w.b1, w.w2, w.b2,
        )
        assert perturbed.content_hash != w.content_hash
        p = str(tmp_path / "p.ggrspred")
        save_artifact(perturbed, p)
        assert load_artifact(p).content_hash == perturbed.content_hash

    def test_typed_refusal_of_bad_bytes(self, tmp_path):
        data = load_default().to_bytes()
        with pytest.raises(ValueError, match="not a GGRSPRED"):
            PredictorWeights.from_bytes(b"XXXXXXXX" + data[8:])
        with pytest.raises(ValueError, match="truncated"):
            PredictorWeights.from_bytes(data[:-4])
        with pytest.raises(ValueError, match="trailing"):
            PredictorWeights.from_bytes(data + b"\x00")

    def test_resolve_config_env_semantics(self, monkeypatch):
        monkeypatch.delenv("GGRS_PREDICTOR", raising=False)
        assert resolve_predictor_config(None) is None
        for off in ("0", "off", "false"):
            monkeypatch.setenv("GGRS_PREDICTOR", off)
            assert resolve_predictor_config(None) is None
        monkeypatch.setenv("GGRS_PREDICTOR", "1")
        ip = resolve_predictor_config(None)
        assert isinstance(ip, InputPredictor)
        assert ip.content_hash == load_default().content_hash
        # False forces OFF even when the env says on.
        assert resolve_predictor_config(False) is None
        monkeypatch.setenv("GGRS_PREDICTOR", DEFAULT_ARTIFACT)
        assert (resolve_predictor_config(None).content_hash
                == load_default().content_hash)
        with pytest.raises(TypeError):
            resolve_predictor_config(3.14)


# --------------------------------------------------------------------------
# Handshake refusal
# --------------------------------------------------------------------------


def _p2p_builder(me, predictor):
    builder = (
        SessionBuilder(box_game.INPUT_SPEC)
        .with_num_players(2)
        .with_max_prediction_window(MAXPRED)
    )
    if predictor is not None:
        builder.with_input_predictor(predictor)
    for h in range(2):
        if h == me:
            builder.add_player(PlayerType.local(), h)
        else:
            builder.add_player(PlayerType.remote(("peer", h)), h)
    return builder


class TestHandshake:
    def test_digest_mismatch_is_typed_refusal(self, monkeypatch):
        """ON host vs OFF peer: neither synchronizes, both surface one
        CONFIG_MISMATCH event carrying the two digests — no desync, no
        progress."""
        monkeypatch.delenv("GGRS_PREDICTOR", raising=False)
        net = LoopbackNetwork()
        sessions = [
            _p2p_builder(0, True).start_p2p_session(
                net.socket(("peer", 0)), clock=lambda: net.now
            ),
            _p2p_builder(1, False).start_p2p_session(
                net.socket(("peer", 1)), clock=lambda: net.now
            ),
        ]
        events = []
        for _ in range(120):
            net.advance(FPS_DT)
            for s in sessions:
                s.poll_remote_clients()
                events.extend(s.events())
        for s in sessions:
            assert s.current_state() != SessionState.RUNNING
        mismatches = [e for e in events
                      if e.kind == EventKind.CONFIG_MISMATCH]
        assert mismatches, "refusal never surfaced as a typed event"
        digest = load_default().content_hash
        for e in mismatches:
            assert {e.data["local_digest"], e.data["peer_digest"]} == {
                0, digest,
            }
        assert not any(e.kind == EventKind.DESYNC_DETECTED for e in events)

    def test_matching_digests_synchronize(self, monkeypatch):
        monkeypatch.delenv("GGRS_PREDICTOR", raising=False)
        net = LoopbackNetwork()
        sessions = [
            _p2p_builder(me, True).start_p2p_session(
                net.socket(("peer", me)), clock=lambda: net.now
            )
            for me in range(2)
        ]
        for _ in range(30):
            net.advance(FPS_DT)
            for s in sessions:
                s.poll_remote_clients()
                s.events()
        assert all(
            s.current_state() == SessionState.RUNNING for s in sessions
        )

    def test_builder_digest_resolution(self, monkeypatch):
        monkeypatch.delenv("GGRS_PREDICTOR", raising=False)
        b = SessionBuilder(box_game.INPUT_SPEC)
        assert b._config_digest() == 0
        b.with_input_predictor(True)
        assert b._config_digest() == load_default().content_hash
        b.with_input_predictor(False)
        assert b._config_digest() == 0
        with pytest.raises((TypeError, OSError, ValueError)):
            b.with_input_predictor("/nonexistent/weights.ggrspred")


# --------------------------------------------------------------------------
# Seeded branch trees: native vs Python, batched vs host
# --------------------------------------------------------------------------


class _Bag:
    """The singleton runner's tree builders, unbound (the same borrow
    the batched serve shim uses)."""

    _candidate_values = SpeculativeRollbackRunner._candidate_values
    _extrapolate_base = SpeculativeRollbackRunner._extrapolate_base
    _structured_bits = SpeculativeRollbackRunner._structured_bits
    _history_fingerprint = SpeculativeRollbackRunner._history_fingerprint

    def __init__(self, spec, players, branches, frames, values):
        self.input_spec = spec
        self.num_players = players
        self.num_branches = branches
        self.spec_frames = frames
        self._branch_values = values
        self._input_log = {}


@pytest.mark.skipif(not ncore.available(), reason="native core unavailable")
def test_seeded_tree_native_python_parity():
    """Randomized: predictor-seeded trees agree bitwise between builders,
    the seed changes the dedup signature, the seeded signature dedup-skips,
    and branch 0 stays literal repeat-last."""
    rng = np.random.RandomState(7)
    bound_cache = {}
    for trial in range(12):
        players = int(rng.choice([2, 4]))
        frames = int(rng.choice([4, 8]))
        branches = int(rng.choice([8, 64]))
        spec = InputSpec(shape=(), dtype=np.uint8, values=tuple(UNIVERSE))
        bag = _Bag(spec, players, branches, frames, UNIVERSE)
        nat = native_spec.make_spec_builder(
            spec, players, branches, frames, UNIVERSE
        )
        assert nat is not None
        if players not in bound_cache:
            bound_cache[players] = InputPredictor(load_default()).bind(
                UNIVERSE, np.uint8, 1
            )
        bound = bound_cache[players]
        keys = [1, 8, 2, 0]
        n_log = int(rng.randint(0, 24))
        for f in range(n_log):
            row = np.array(
                [keys[(f // 3 + h) % 4] for h in range(players)],
                dtype=np.uint8,
            )
            if rng.rand() < 0.1:
                row = rng.randint(0, 16, size=players).astype(np.uint8)
            bag._input_log[f] = row
            nat.log_set(f, row)
        anchor = n_log
        last = bag._input_log.get(anchor - 1)
        if last is None:
            last = spec.zeros_np(players)
        known = np.zeros((frames, players), dtype=np.uint8)
        mask = np.zeros((frames, players), dtype=bool)
        for p in range(players):
            k = rng.randint(0, frames)
            mask[:k, p] = True
            known[:k, p] = rng.randint(0, 16, size=k)

        seed = bound.seed(bag._input_log, anchor, frames, players)
        py_off = bag._structured_bits(np.asarray(last), known, mask, anchor)
        nb_off, sig_off = nat.build(anchor, None, known, mask, False, None)
        assert np.array_equal(py_off, nb_off)

        bag._predictor = bound
        bag._seed_memo = None
        py_on = bag._structured_bits(np.asarray(last), known, mask, anchor)
        del bag._predictor
        nat.seed(anchor, seed)
        nb_on, sig_on = nat.build(anchor, None, known, mask, False, None)
        assert np.array_equal(py_on, nb_on)
        if n_log > 0:
            assert sig_on != sig_off  # the seed is part of tree identity
        # Seeded dedup skip: same seed + same signature -> no rebuild.
        nat.seed(anchor, seed)
        nb2, sig2 = nat.build(anchor, None, known, mask, True, sig_on)
        assert nb2 is None and sig2 == sig_on
        # Branch 0 repeat-last survives seeding, in both builders.
        assert np.array_equal(py_on[0], py_off[0])
        assert np.array_equal(nb_on[0], py_off[0])


def test_batched_ranker_matches_host_rollout():
    from bevy_ggrs_tpu.predict.batch import BatchedRanker

    bound = InputPredictor(load_default()).bind(UNIVERSE, np.uint8, 1)
    frames, S, P = 6, 5, 2
    ranker = BatchedRanker(bound, frames)
    rng = np.random.RandomState(11)
    wins = rng.randint(-1, len(UNIVERSE), size=(S, bound.weights.window, P))
    wins = wins.astype(np.int32)
    anchors = rng.randint(0, 200, size=S).astype(np.int32)
    traj, order = ranker.rank(wins, anchors)
    V = len(UNIVERSE)
    for s in range(S):
        htraj, hlogits = bound.rollout(wins[s], int(anchors[s]), frames)
        horder = np.argsort(
            -hlogits[:, :V], axis=1, kind="stable"
        ).astype(np.int32)
        assert np.array_equal(traj[s], htraj)
        assert np.array_equal(order[s], horder)
        # The rendered seeds agree too (shared render_seed path).
        assert (bound.render_seed(traj[s], order[s]).fold_bytes()
                == bound.render_seed(htraj, horder).fold_bytes())


def test_ledger_policy_registry_has_learned():
    from bevy_ggrs_tpu.obs import ledger

    assert set(ledger.POLICIES) >= {"current", "repeat_last", "learned"}
    import json

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "spec_baseline.json")) as f:
        base = json.load(f)
    assert set(base["policies"]) >= {"current", "repeat_last", "learned"}
    for name, cfg in base["configs"].items():
        pol = cfg["policies"]
        # The committed acceptance: learned strictly above repeat-last
        # everywhere, and at least matching the live heuristic.
        assert pol["learned"]["full_hit_rate"] > (
            pol["repeat_last"]["full_hit_rate"]
        ), name
        assert pol["learned"]["full_hit_rate"] >= (
            pol["current"]["full_hit_rate"]
        ), name


# --------------------------------------------------------------------------
# Live sessions: wire invisibility + OFF identity
# --------------------------------------------------------------------------


class _RecordingSocket:
    def __init__(self, inner, tape):
        self._inner = inner
        self.tape = tape
        self.addr = inner.addr

    def send_to(self, msg, addr):
        self.tape.append(bytes(msg))
        self._inner.send_to(msg, addr)

    def receive_all(self):
        return self._inner.receive_all()

    def close(self):
        self._inner.close()


def _run_spec_pair(predictor, iters=180, latency=1.5 * FPS_DT):
    """A full predictor-configured P2P run: two spec runners, scripted
    inputs, injected latency (real rollbacks), every sent datagram
    taped. Returns (peers, tapes, events)."""
    net = LoopbackNetwork(latency=latency, seed=5)
    peers, tapes = [], []
    for me in range(2):
        tape = []
        sock = _RecordingSocket(net.socket(("peer", me)), tape)
        session = _p2p_builder(me, predictor).start_p2p_session(
            sock, clock=lambda: net.now
        )
        runner = SpeculativeRollbackRunner(
            box_game.make_schedule(), box_game.make_world(2).commit(),
            max_prediction=MAXPRED, num_players=2,
            input_spec=box_game.INPUT_SPEC, num_branches=16, spec_frames=4,
            predictor=predictor,
        )
        peers.append((session, runner))
        tapes.append(tape)
    events = []
    for _ in range(iters):
        net.advance(FPS_DT)
        for session, runner in peers:
            session.poll_remote_clients()
            events.extend(session.events())
            if session.current_state() != SessionState.RUNNING:
                continue
            for h in session.local_player_handles():
                session.add_local_input(
                    h, scripted_input(h, session.current_frame)
                )
            try:
                requests = session.advance_frame()
            except PredictionThreshold:
                continue
            runner.handle_requests(requests, session)
            runner.speculate(session.confirmed_frame(), session)
    return peers, tapes, events


def _split_sync(tape):
    sync, rest = [], []
    for msg in tape:
        decoded = proto.decode(msg)
        if isinstance(decoded, (proto.SyncRequest, proto.SyncReply)):
            sync.append(msg)
        else:
            rest.append(msg)
    return sync, rest


@pytest.mark.slow
def test_predictor_on_wire_invisible(monkeypatch):
    """The whole point of the determinism contract: a predictor-ON pair's
    traffic is byte-identical to the OFF pair's outside the handshake
    digest, trajectories agree bitwise across ON/OFF AND across peers,
    and no desync fires — speculation internals never reach the wire."""
    monkeypatch.delenv("GGRS_PREDICTOR", raising=False)
    on_peers, on_tapes, on_events = _run_spec_pair(True)
    off_peers, off_tapes, off_events = _run_spec_pair(False)
    for events in (on_events, off_events):
        assert not any(
            e.kind in (EventKind.DESYNC_DETECTED, EventKind.CONFIG_MISMATCH)
            for e in events
        )
    # The predictor actually ran in the ON pair.
    for _, runner in on_peers:
        assert runner._predictor is not None
        assert runner.predictor_rank_builds > 0
    for _, runner in off_peers:
        assert runner._predictor is None
    # Wire invisibility: everything but the sync handshake is
    # byte-identical in order; the handshake differs only by carrying a
    # different digest (same message count).
    for on_tape, off_tape in zip(on_tapes, off_tapes):
        on_sync, on_rest = _split_sync(on_tape)
        off_sync, off_rest = _split_sync(off_tape)
        assert on_rest == off_rest
        assert len(on_sync) == len(off_sync)
    # Bitwise trajectories: peers agree with each other and across runs.
    frames_on, pairs_on = common_confirmed_checksums(on_peers)
    frames_off, pairs_off = common_confirmed_checksums(off_peers)
    assert frames_on and all(a == b for a, b in pairs_on)
    assert frames_off and all(a == b for a, b in pairs_off)
    common = sorted(set(frames_on) & set(frames_off))
    assert common
    cs_on = dict(zip(frames_on, (a for a, _ in pairs_on)))
    cs_off = dict(zip(frames_off, (a for a, _ in pairs_off)))
    assert all(cs_on[f] == cs_off[f] for f in common)


def test_predictor_off_identical_to_unconfigured(monkeypatch):
    """predictor=False and a plain unconfigured runner run the same
    script to bitwise-identical state — the OFF path has zero behavioral
    surface (the pre-PR identity witness backing the CI matrix's OFF
    legs)."""
    monkeypatch.delenv("GGRS_PREDICTOR", raising=False)
    from bevy_ggrs_tpu.state import checksum, combine64

    def run(**kw):
        r = SpeculativeRollbackRunner(
            box_game.make_schedule(), box_game.make_world(2).commit(),
            max_prediction=4, num_players=2,
            input_spec=box_game.INPUT_SPEC, num_branches=8, spec_frames=3,
            **kw,
        )
        r.warmup()
        from bevy_ggrs_tpu.session.requests import (
            AdvanceFrame, LoadGameState, SaveGameState,
        )

        frame = 0
        for cycle in range(4):
            for _ in range(3):
                bits = np.array(
                    [scripted_input(h, frame) for h in range(2)], np.uint8
                )
                r.tick(
                    [SaveGameState(frame),
                     AdvanceFrame(bits=bits,
                                  status=np.zeros(2, np.int32))],
                    frame, None,
                )
                frame += 1
            # A depth-2 rollback per cycle.
            reqs = [LoadGameState(frame - 2)]
            for f in range(frame - 2, frame + 1):
                bits = np.array(
                    [scripted_input(h, f) ^ (1 if f < frame else 0)
                     for h in range(2)], np.uint8,
                )
                reqs += [SaveGameState(f),
                         AdvanceFrame(bits=bits,
                                      status=np.zeros(2, np.int32))]
            r.tick(reqs, frame, None)
            frame += 1
        return r

    plain, off = run(), run(predictor=False)
    assert plain._predictor is None and off._predictor is None
    assert plain.frame == off.frame
    assert combine64(checksum(plain.state)) == combine64(
        checksum(off.state)
    )
    assert np.array_equal(
        np.asarray(plain.ring.checksums), np.asarray(off.ring.checksums)
    )
