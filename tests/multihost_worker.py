"""Worker process for the two-process DCN smoke test.

Each of two OS processes owns 4 virtual CPU devices; `jax.distributed`
rendezvous at a real TCP coordinator makes them one 8-device cluster. The
worker then drives the REAL multihost path end to end: global [branch]
mesh (branch blocks host-local, multihost.py layout rule), a speculative
rollout whose branch axis spans both processes, a cross-process
confirmed-branch commit (the one collective that rides DCN), and a final
checksum allgather proving both processes computed the same world.

Two phases: (1) a branch-sharded speculative rollout with a cross-process
confirmed-branch commit; (2) a live SyncTest session in SPMD lockstep with
the world/ring entity-sharded across the processes (every rollback a
cross-DCN collective).

Usage: python multihost_worker.py <process_id> <num_processes> <port>
Prints one line: ``MULTIHOST_OK <process_id> <rollout-checksum-hex>
live=<live-session-checksum-hex>``.
"""

import os
import sys


def main() -> None:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    import numpy as np

    from bevy_ggrs_tpu.models import box_game
    from bevy_ggrs_tpu.parallel import multihost
    from bevy_ggrs_tpu.parallel.speculate import SpeculativeExecutor
    from bevy_ggrs_tpu.state import checksum, combine64

    got_pid, got_nproc = multihost.initialize(
        f"127.0.0.1:{port}", nproc, pid
    )
    assert (got_pid, got_nproc) == (pid, nproc), (got_pid, got_nproc)
    assert jax.process_count() == nproc
    assert len(jax.local_devices()) == 4
    assert len(jax.devices()) == 4 * nproc

    topo = multihost.process_topology()
    assert topo["process_index"] == pid

    B, F, P = 8, 4, 2
    mesh = multihost.global_branch_mesh()
    schedule = box_game.make_schedule()
    state = box_game.make_world(P).commit()

    # Every process materializes the same full branch tensor (same seed)
    # and contributes its local block — the local_branch_slice contract.
    rng = np.random.RandomState(7)
    host_bits = rng.randint(0, 16, (B, F, P), dtype=np.uint8)
    start, stop = multihost.local_branch_slice(B)
    assert stop - start == B // nproc and start == pid * (B // nproc)

    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(mesh, PartitionSpec("branch"))
    bits = jax.make_array_from_callback(
        host_bits.shape, sharding, lambda idx: host_bits[idx]
    )

    ex = SpeculativeExecutor(schedule, B, F, mesh=mesh)
    res = ex.run(state, 0, bits)
    # Confirmed-branch commit: branch 5 lives on the OTHER process for
    # pid 0 — this gather is the cross-DCN collective.
    ring, final_state = ex.commit(res, 5)
    cs = combine64(np.asarray(jax.device_get(checksum(final_state))))

    from jax.experimental import multihost_utils

    everyone = multihost_utils.process_allgather(
        np.asarray([cs & 0xFFFFFFFF, cs >> 32], np.uint32)
    )
    assert everyone.shape[0] == nproc
    for other in range(nproc):
        assert (everyone[other] == everyone[pid]).all(), (
            f"checksum divergence across processes: {everyone}"
        )

    # --- Phase 2: a LIVE session spanning both processes. Multi-controller
    # SPMD requires every process to issue the same jit calls in lockstep;
    # the sound multihost session model (multihost.py docstring) is
    # deterministic replication of the host-side protocol — here a
    # SyncTest whose scripted inputs are identical on both processes, so
    # both emit identical request lists while the runner's world + ring
    # live SHARDED across the two processes' devices (the entity axis
    # spans DCN; every rollback's fused scan runs as cross-process
    # collectives).
    from bevy_ggrs_tpu.runner import RollbackRunner
    from bevy_ggrs_tpu.session import SyncTestSession

    mesh2 = multihost.global_branch_mesh(entity_shards=len(jax.devices()))
    session = SyncTestSession(
        P, box_game.INPUT_SPEC, check_distance=2, max_prediction=4
    )
    runner = RollbackRunner(
        schedule, box_game.make_world(P).commit(),
        max_prediction=4, num_players=P, input_spec=box_game.INPUT_SPEC,
        mesh=mesh2,
    )
    rng2 = np.random.RandomState(42)  # same stream on both processes
    for _ in range(10):
        for h in range(P):
            session.add_local_input(h, np.uint8(rng2.randint(0, 16)))
        runner.handle_requests(session.advance_frame(), session)
    assert runner.frame == 10
    assert not runner.state.components[
        "translation"
    ].sharding.is_fully_replicated
    live_cs = combine64(np.asarray(jax.device_get(checksum(runner.state))))
    everyone2 = multihost_utils.process_allgather(
        np.asarray([live_cs & 0xFFFFFFFF, live_cs >> 32], np.uint32)
    )
    for other in range(nproc):
        assert (everyone2[other] == everyone2[pid]).all(), (
            f"live-session divergence across processes: {everyone2}"
        )

    print(f"MULTIHOST_OK {pid} {cs:#x} live={live_cs:#x}", flush=True)


if __name__ == "__main__":
    main()
