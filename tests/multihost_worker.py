"""Worker process for the two-process DCN smoke test.

Each of two OS processes owns 4 virtual CPU devices; `jax.distributed`
rendezvous at a real TCP coordinator makes them one 8-device cluster. The
worker then drives the REAL multihost path end to end: global [branch]
mesh (branch blocks host-local, multihost.py layout rule), a speculative
rollout whose branch axis spans both processes, a cross-process
confirmed-branch commit (the one collective that rides DCN), and a final
checksum allgather proving both processes computed the same world.

Usage: python multihost_worker.py <process_id> <num_processes> <port>
Prints one line: ``MULTIHOST_OK <process_id> <checksum-hex>``.
"""

import os
import sys


def main() -> None:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    import numpy as np

    from bevy_ggrs_tpu.models import box_game
    from bevy_ggrs_tpu.parallel import multihost
    from bevy_ggrs_tpu.parallel.speculate import SpeculativeExecutor
    from bevy_ggrs_tpu.state import checksum, combine64

    got_pid, got_nproc = multihost.initialize(
        f"127.0.0.1:{port}", nproc, pid
    )
    assert (got_pid, got_nproc) == (pid, nproc), (got_pid, got_nproc)
    assert jax.process_count() == nproc
    assert len(jax.local_devices()) == 4
    assert len(jax.devices()) == 4 * nproc

    topo = multihost.process_topology()
    assert topo["process_index"] == pid

    B, F, P = 8, 4, 2
    mesh = multihost.global_branch_mesh()
    schedule = box_game.make_schedule()
    state = box_game.make_world(P).commit()

    # Every process materializes the same full branch tensor (same seed)
    # and contributes its local block — the local_branch_slice contract.
    rng = np.random.RandomState(7)
    host_bits = rng.randint(0, 16, (B, F, P), dtype=np.uint8)
    start, stop = multihost.local_branch_slice(B)
    assert stop - start == B // nproc and start == pid * (B // nproc)

    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(mesh, PartitionSpec("branch"))
    bits = jax.make_array_from_callback(
        host_bits.shape, sharding, lambda idx: host_bits[idx]
    )

    ex = SpeculativeExecutor(schedule, B, F, mesh=mesh)
    res = ex.run(state, 0, bits)
    # Confirmed-branch commit: branch 5 lives on the OTHER process for
    # pid 0 — this gather is the cross-DCN collective.
    ring, final_state = ex.commit(res, 5)
    cs = combine64(np.asarray(jax.device_get(checksum(final_state))))

    from jax.experimental import multihost_utils

    everyone = multihost_utils.process_allgather(
        np.asarray([cs & 0xFFFFFFFF, cs >> 32], np.uint32)
    )
    assert everyone.shape[0] == nproc
    for other in range(nproc):
        assert (everyone[other] == everyone[pid]).all(), (
            f"checksum divergence across processes: {everyone}"
        )

    print(f"MULTIHOST_OK {pid} {cs:#x}", flush=True)


if __name__ == "__main__":
    main()
