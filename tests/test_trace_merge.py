"""Cross-process frame provenance + trace merge.

The tentpole contract: a passive :class:`SidecarSocket` tap on each
process's raw socket records every datagram with an FNV-1a flow key;
because the relay forwards envelope bytes verbatim, the same key appears
at peer-tx, relay-rx, relay-tx, and destination-rx — so
:func:`merge_traces` can stitch per-process exports into ONE Perfetto
timeline where a single input's journey spans the peer, relay, and
destination tracks as flow arrows, with zero telemetry bytes on the
wire."""

import json

import numpy as np
import pytest

from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.obs import (
    ProvenanceLog,
    SidecarSocket,
    SpanTracer,
    flow_key,
    follow,
    frame_flows,
    merge_traces,
)
from bevy_ggrs_tpu.obs.merge import WIRE_TID, main as merge_main
from bevy_ggrs_tpu.obs.provenance import _classify
from bevy_ggrs_tpu.relay import RelayServer, RelaySocket, peer_addr
from bevy_ggrs_tpu.runner import RollbackRunner
from bevy_ggrs_tpu.session import (
    PlayerType,
    PredictionThreshold,
    SessionBuilder,
    SessionState,
    protocol,
)
from bevy_ggrs_tpu.transport.loopback import LoopbackNetwork
from tests.test_p2p import FPS_DT, scripted_input


def input_datagram(start_frame=7, handle=0):
    return protocol.encode(
        protocol.InputMsg(
            handle=handle, start_frame=start_frame, payload=b"\x01",
            num=1, ack_frame=-1, sender_frame=9, advantage=0,
        )
    )


class TestFlowKey:
    def test_deterministic_and_content_sensitive(self):
        a = input_datagram(7)
        assert flow_key(a) == flow_key(bytes(a))
        assert flow_key(a) != flow_key(input_datagram(8))
        assert 0 <= flow_key(b"") < 2 ** 64

    def test_relay_envelope_digest_differs_from_inner(self):
        inner = input_datagram(7)
        fwd = protocol.encode(protocol.RelayForward(0, 1, inner))
        assert flow_key(fwd) != flow_key(inner)


class TestClassify:
    def test_input_carries_its_start_frame(self):
        tag, frame, inner = _classify(input_datagram(start_frame=42))
        assert (tag, frame, inner) == ("input", 42, None)

    def test_relay_forward_classifies_the_inner_datagram(self):
        fwd = protocol.encode(
            protocol.RelayForward(0, 1, input_datagram(start_frame=5))
        )
        tag, frame, inner = _classify(fwd)
        assert tag == "relay_forward"
        assert inner == "input" and frame == 5

    def test_stream_and_checksum_frames(self):
        cs = protocol.encode(protocol.ChecksumReport(frame=11, checksum=3))
        assert _classify(cs)[:2] == ("checksum_report", 11)

    def test_garbage_is_tagged_not_raised(self):
        assert _classify(b"")[0] == "garbage"
        assert _classify(b"\x00" * 16)[0] == "garbage"
        # Truncated body after a valid header: tag survives, frame is None.
        hdr = protocol._HDR.pack(protocol.MAGIC, protocol.VERSION,
                                 protocol.T_INPUT)
        assert _classify(hdr)[:2] == ("input", None)


class TestSidecarSocket:
    def test_records_tx_rx_and_forwards_verbatim(self):
        net = LoopbackNetwork()
        log_a = ProvenanceLog("a", pid=0, clock=lambda: net.now)
        log_b = ProvenanceLog("b", pid=1, clock=lambda: net.now)
        sa = SidecarSocket(net.socket(("peer", 0)), log_a)
        sb = SidecarSocket(net.socket(("peer", 1)), log_b)
        msg = input_datagram(3)
        sa.send_to(msg, ("peer", 1))
        net.advance(FPS_DT)
        got = sb.receive_all()
        assert got == [(("peer", 0), msg)]  # verbatim pass-through
        (tx,), (rx,) = log_a.records(), log_b.records()
        assert tx["dir"] == "tx" and rx["dir"] == "rx"
        assert tx["key"] == rx["key"] == flow_key(msg)
        assert tx["frame"] == rx["frame"] == 3
        assert tx["type"] == "input"

    def test_context_rides_records_not_payloads(self):
        net = LoopbackNetwork()
        log = ProvenanceLog("a", clock=lambda: net.now)
        s = SidecarSocket(net.socket(("peer", 0)), log)
        msg = input_datagram(1)
        log.set_context(match=17, epoch=2)
        s.send_to(msg, ("peer", 1))
        log.set_context(match=None)
        s.send_to(msg, ("peer", 1))
        first, second = log.records()
        assert first["match"] == 17 and first["epoch"] == 2
        assert "match" not in second and second["epoch"] == 2
        # Same payload, same key: context never touched the bytes.
        assert first["key"] == second["key"]

    def test_capacity_bounds_the_ring(self):
        log = ProvenanceLog("a", capacity=4)
        for i in range(10):
            log.record("tx", input_datagram(i), ("x", 0))
        recs = log.records()
        assert len(recs) == 4 and recs[-1]["frame"] == 9

    def test_delegates_beyond_protocol_surface(self):
        net = LoopbackNetwork()
        s = SidecarSocket(net.socket(("peer", 5)), ProvenanceLog("a"))
        assert s.addr == ("peer", 5)

    def test_jsonl_round_trip(self, tmp_path):
        log = ProvenanceLog("peer0", pid=2, wall_t0=50.0)
        log.record("tx", input_datagram(1), ("peer", 1))
        p = tmp_path / "prov.jsonl"
        assert log.export_jsonl(str(p)) == 1
        lines = [json.loads(l) for l in p.read_text().splitlines()]
        assert lines[0]["meta"] == {
            "component": "peer0", "pid": 2, "wall_t0": 50.0,
        }
        assert lines[1]["dir"] == "tx" and lines[1]["frame"] == 1


def run_relayed_pair(tmp_path, frames=90):
    """Two peers whose only transport is a relay, each raw socket (and
    the relay's) wrapped in a sidecar tap; returns the exported
    per-component provenance paths + a relay trace path."""
    net = LoopbackNetwork()
    logs = []

    def tap(sock, component, pid):
        log = ProvenanceLog(component, pid=pid, clock=lambda: net.now)
        logs.append(log)
        return SidecarSocket(sock, log)

    relay_tracer = SpanTracer(clock=lambda: net.now, pid=100,
                              process_name="relay")
    relay = RelayServer(
        tap(net.socket(("relay", 0)), "relay", 100),
        clock=lambda: net.now, tracer=relay_tracer,
    )
    peers = []
    for me in range(2):
        rsock = RelaySocket(
            tap(net.socket(("peer", me)), f"peer{me}", me),
            [("relay", 0)], session_id=1, peer_id=me,
            clock=lambda: net.now,
        )
        builder = (
            SessionBuilder(box_game.INPUT_SPEC)
            .with_num_players(2)
            .with_max_prediction_window(8)
        )
        for h in range(2):
            builder.add_player(
                PlayerType.local() if h == me
                else PlayerType.remote(peer_addr(h)), h,
            )
        session = builder.start_p2p_session(rsock, clock=lambda: net.now)
        runner = RollbackRunner(
            box_game.make_schedule(), box_game.make_world(2).commit(),
            max_prediction=8, num_players=2,
            input_spec=box_game.INPUT_SPEC,
        )
        peers.append((session, runner))
    for _ in range(frames):
        net.advance(FPS_DT)
        relay.pump(net.now)
        for session, runner in peers:
            session.poll_remote_clients()
            if session.current_state() != SessionState.RUNNING:
                continue
            for h in session.local_player_handles():
                session.add_local_input(h, scripted_input(
                    h, session.current_frame))
            try:
                runner.handle_requests(session.advance_frame(), session)
            except PredictionThreshold:
                pass
    assert all(s.current_frame >= 40 for s, _ in peers)
    prov_paths = []
    for log in logs:
        p = tmp_path / f"{log.component}.jsonl"
        log.export_jsonl(str(p))
        prov_paths.append(str(p))
    trace_path = tmp_path / "relay_trace.json"
    relay_tracer.export_perfetto(str(trace_path))
    return prov_paths, str(trace_path)


class TestCrossProcessFlows:
    def test_one_input_spans_four_hops_in_causal_order(self, tmp_path):
        """Acceptance: follow one input peer0 -> relay -> peer1. The
        verbatim-forwarding relay gives all four hops the same digest;
        the chain comes back tx -> rx -> tx -> rx across components even
        at identical virtual timestamps."""
        prov_paths, _ = run_relayed_pair(tmp_path)
        flows = frame_flows(prov_paths, 30)
        four_hop = {
            k: chain for k, chain in flows.items() if len(chain) == 4
        }
        assert four_hop, "no input reached all four hops"
        for key, chain in four_hop.items():
            comps = [c for c, _ in chain]
            dirs = [r["dir"] for _, r in chain]
            assert dirs == ["tx", "rx", "tx", "rx"]
            assert comps[1] == comps[2] == "relay"
            assert {comps[0], comps[3]} <= {"peer0", "peer1"}
            assert comps[0] != comps[3]
            # follow() on the key reproduces the same chain.
            assert follow(prov_paths, key) == chain
            # Every hop agrees on the wire form (the envelope).
            assert {r["type"] for _, r in chain} == {"relay_forward"}
            assert {r["inner"] for _, r in chain} == {"input"}

    def test_merged_trace_links_hops_with_flow_events(self, tmp_path):
        prov_paths, trace_path = run_relayed_pair(tmp_path)
        out = tmp_path / "merged.json"
        trace = merge_traces([trace_path], prov_paths, path=str(out))
        assert json.loads(out.read_text()) == trace
        ev = trace["traceEvents"]
        # Every provenance component got a named wire track.
        wire_tracks = {
            e["args"]["name"]
            for e in ev
            if e["ph"] == "M" and e["name"] == "thread_name"
            and e["args"]["name"].startswith("wire:")
        }
        assert wire_tracks == {"wire:relay", "wire:peer0", "wire:peer1"}
        # Flow chains exist, start/step/finish balanced, and every flow
        # event lands at a (pid, tid, ts) where a wire slice exists.
        starts = [e for e in ev if e["ph"] == "s"]
        finishes = [e for e in ev if e["ph"] == "f"]
        assert starts and len(starts) == len(finishes)
        slices = {
            (e["pid"], e["tid"], e["ts"])
            for e in ev if e["ph"] == "X"
        }
        for e in ev:
            if e["ph"] in ("s", "t", "f"):
                assert e["tid"] == WIRE_TID
                assert (e["pid"], e["tid"], e["ts"]) in slices
        # At least one flow id spans three distinct processes.
        flow_pids = {}
        for e in ev:
            if e["ph"] in ("s", "t", "f"):
                flow_pids.setdefault(e["id"], set()).add(e["pid"])
        assert any(len(pids) >= 3 for pids in flow_pids.values())

    def test_pid_collision_between_files_is_remapped(self, tmp_path):
        a, b = SpanTracer(pid=0, process_name="a"), SpanTracer(
            pid=0, process_name="b")
        for t in (a, b):
            with t.span("net_poll"):
                pass
        pa, pb = tmp_path / "a.json", tmp_path / "b.json"
        a.export_perfetto(str(pa))
        b.export_perfetto(str(pb))
        trace = merge_traces([str(pa), str(pb)])
        pids = {
            e["args"]["name"]: e["pid"]
            for e in trace["traceEvents"]
            if e.get("name") == "process_name"
        }
        assert pids["a"] != pids["b"]

    def test_wall_alignment_shifts_by_anchor_delta(self, tmp_path):
        a = SpanTracer(pid=0, process_name="a", wall_t0=100.0)
        b = SpanTracer(pid=1, process_name="b", wall_t0=100.5)
        for t in (a, b):
            with t.span("net_poll"):
                pass
        pa, pb = tmp_path / "a.json", tmp_path / "b.json"
        a.export_perfetto(str(pa))
        b.export_perfetto(str(pb))
        trace = merge_traces([str(pa), str(pb)], align="wall")
        ts_by_pid = {}
        for e in trace["traceEvents"]:
            if e["ph"] == "B":
                ts_by_pid[e["pid"]] = e["ts"]
        # b's events moved +500ms relative to a's (anchor = min wall_t0).
        assert ts_by_pid[1] - ts_by_pid[0] == pytest.approx(500_000, abs=2_000)

    def test_cli_merges_and_reports(self, tmp_path, capsys):
        prov_paths, trace_path = run_relayed_pair(tmp_path)
        out = tmp_path / "cli_merged.json"
        rc = merge_main(
            [trace_path, "--provenance", *prov_paths, "--out", str(out)]
        )
        assert rc == 0
        assert "flow hops" in capsys.readouterr().out
        assert json.loads(out.read_text())["traceEvents"]
