"""3+ player sessions: disconnect convergence, handle ownership, spectator
history retention."""

import numpy as np

from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.runner import RollbackRunner
from bevy_ggrs_tpu.session import (
    EventKind,
    PlayerType,
    PredictionThreshold,
    SessionBuilder,
    SessionState,
)
from bevy_ggrs_tpu.session import protocol as proto
from bevy_ggrs_tpu.session.endpoint import PeerState
from bevy_ggrs_tpu.transport.loopback import LoopbackNetwork
from tests.test_p2p import FPS_DT, scripted_input


def make_group(net, n, max_prediction=8, disconnect_timeout=0.5, spectators=()):
    peers = []
    for me in range(n):
        sock = net.socket(("peer", me))
        builder = (
            SessionBuilder(box_game.INPUT_SPEC)
            .with_num_players(n)
            .with_max_prediction_window(max_prediction)
            .with_disconnect_timeout(disconnect_timeout)
        )
        for h in range(n):
            builder.add_player(
                PlayerType.local() if h == me else PlayerType.remote(("peer", h)), h
            )
        if me == 0:
            for addr in spectators:
                builder.add_player(PlayerType.spectator(addr), n + 1)
        session = builder.start_p2p_session(sock, clock=lambda: net.now)
        runner = RollbackRunner(
            box_game.make_schedule(),
            box_game.make_world(n).commit(),
            max_prediction=max_prediction,
            num_players=n,
            input_spec=box_game.INPUT_SPEC,
        )
        peers.append((session, runner))
    return peers


def step_peer(session, runner, inputs_for):
    session.poll_remote_clients()
    if session.current_state() != SessionState.RUNNING:
        return
    for h in session.local_player_handles():
        session.add_local_input(h, inputs_for(h, session.current_frame))
    try:
        runner.handle_requests(session.advance_frame(), session)
    except PredictionThreshold:
        pass


class TestThreePlayers:
    def test_three_player_consistency(self):
        net = LoopbackNetwork(latency=2 * FPS_DT)
        peers = make_group(net, 3)
        for _ in range(80):
            net.advance(FPS_DT)
            for s, r in peers:
                step_peer(s, r, scripted_input)
        sessions = [s for s, _ in peers]
        upto = min(s.confirmed_frame() for s in sessions)
        assert upto > 30
        base = sessions[0]._local_checksums
        for s in sessions[1:]:
            common = [f for f in base if f <= upto and f in s._local_checksums]
            assert len(common) >= 2  # exchange-interval frames only
            assert all(base[f] == s._local_checksums[f] for f in common)

    def test_survivors_converge_after_disconnect(self):
        """When C dies, survivors may hold different amounts of C's input
        history (here: asymmetric latency). The survivor relay must bring
        them to the same confirmed trajectory — no spurious desync."""
        net = LoopbackNetwork(latency=2 * FPS_DT, jitter=2 * FPS_DT, seed=5)
        peers = make_group(net, 3, disconnect_timeout=0.3)
        # Run with everyone alive.
        for _ in range(40):
            net.advance(FPS_DT)
            for s, r in peers:
                step_peer(s, r, scripted_input)
        # C (index 2) dies. A and B keep going past the disconnect timeout.
        pre_death = peers[0][0].current_frame
        events = []
        for _ in range(60):
            net.advance(FPS_DT)
            for s, r in peers[:2]:
                step_peer(s, r, scripted_input)
                events.extend(s.events())
        assert any(e.kind == EventKind.DISCONNECTED for e in events)
        (sa, _), (sb, _) = peers[:2]
        # Survivors resumed and advanced well past the stall window...
        assert sa.current_frame > pre_death + 25
        assert sb.current_frame > pre_death + 25
        # ...agree on every common confirmed frame (incl. post-disconnect)...
        upto = min(sa.confirmed_frame(), sb.confirmed_frame())
        common = [
            f for f in sa._local_checksums
            if f <= upto and f in sb._local_checksums
        ]
        assert len(common) >= 3
        mismatches = [f for f in common if sa._local_checksums[f] != sb._local_checksums[f]]
        assert not mismatches, f"survivors desynced at frames {mismatches}"
        # ...and no desync event fired on a healthy (post-C) match.
        assert not any(e.kind == EventKind.DESYNC_DETECTED for e in events)


class TestHandleOwnership:
    def test_forged_input_from_wrong_peer_is_dropped(self):
        net = LoopbackNetwork()
        peers = make_group(net, 3)
        for _ in range(16):
            net.advance(FPS_DT)
            for s, r in peers:
                step_peer(s, r, scripted_input)
        (sa, _), (sb, _), (sc, _) = peers
        before = sa._queues[2].last_confirmed_frame
        # B forges an input claiming to be player 2 (owned by C, alive).
        forged = proto.InputMsg(
            handle=2,
            start_frame=before + 1,
            payload=bytes([0xFF] * 8),
            num=8,
            ack_frame=-1,
            sender_frame=99,
            advantage=0,
        )
        sb_socket = sb.socket
        sb_socket.send_to(proto.encode(forged), ("peer", 0))
        net.advance(FPS_DT)
        sa.poll_remote_clients()
        after = sa._queues[2].last_confirmed_frame
        confirmed_now = sa._queues[2].confirmed(after) if after >= 0 else None
        # The forged 0xFF bytes must not have been accepted for frames C
        # hasn't actually sent.
        assert after <= before + 0 or confirmed_now is None or confirmed_now != 0xFF


class TestSpectatorRetention:
    def test_absent_spectator_accumulates_nothing(self):
        net = LoopbackNetwork()
        peers = make_group(net, 2, spectators=[("ghost", 0)])  # never bound
        for _ in range(120):
            net.advance(FPS_DT)
            for s, r in peers:
                step_peer(s, r, scripted_input)
        host = peers[0][0]
        ep = host._endpoints[("ghost", 0)]
        pending = max((len(d) for d in ep._pending_output.values()), default=0)
        assert pending == 0, "host queued inputs for a spectator that never synced"
        # Cursor stayed frozen so a late join would still get full history.
        assert host._spec_sent[("ghost", 0)] == -1
        # Input history is retained for the frozen cursor (GC floor).
        assert host._queues[0].confirmed(0) is not None
