"""State core tests: SoA world, snapshot ring, checksum semantics.

Mirrors the behavioral contract of the reference snapshot engine
(`/root/reference/src/world_snapshot.rs`): save/restore roundtrip including
entity create/destroy reconciliation, order-insensitive checksum, duplicate
rollback-id rejection.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bevy_ggrs_tpu import (
    HostWorld,
    TypeRegistry,
    checksum,
    combine64,
    init_state,
    ring_init,
    ring_load,
    ring_save,
)


def make_registry():
    reg = TypeRegistry()
    reg.register_component("translation", shape=(3,), dtype=jnp.float32)
    reg.register_component("velocity", shape=(3,), dtype=jnp.float32)
    reg.register_component("player_handle", shape=(), dtype=jnp.int32, default=-1)
    reg.register_resource("frame_count", jnp.int32(0))
    return reg


def make_world(reg, capacity=8):
    w = HostWorld(reg, capacity)
    w.spawn({"translation": [1.0, 2.0, 3.0], "velocity": [0.0, 0.0, 0.0],
             "player_handle": 0}, rollback_id=0)
    w.spawn({"translation": [-1.0, 0.5, 0.0], "velocity": [0.1, 0.0, 0.0],
             "player_handle": 1}, rollback_id=1)
    return w


def test_spawn_commit_roundtrip():
    reg = make_registry()
    state = make_world(reg).commit()
    assert state.capacity == 8
    assert int(state.num_alive()) == 2
    np.testing.assert_array_equal(np.asarray(state.rollback_id[:2]), [0, 1])
    np.testing.assert_allclose(np.asarray(state.components["translation"][0]), [1, 2, 3])
    assert bool(state.present["player_handle"][1])
    assert not bool(state.present["translation"][2])


def test_duplicate_rollback_id_rejected():
    reg = make_registry()
    w = make_world(reg)
    with pytest.raises(ValueError):
        w.spawn({"translation": [0, 0, 0]}, rollback_id=0)


def test_capacity_exhaustion():
    reg = make_registry()
    w = HostWorld(reg, 2)
    w.spawn({}, rollback_id=0)
    w.spawn({}, rollback_id=1)
    with pytest.raises(RuntimeError):
        w.spawn({}, rollback_id=2)


def test_checksum_changes_with_state():
    reg = make_registry()
    state = make_world(reg).commit()
    c0 = combine64(checksum(state))
    moved = state.replace(
        components={**state.components,
                    "translation": state.components["translation"].at[0, 0].add(1.0)}
    )
    assert combine64(checksum(moved)) != c0


def test_checksum_order_insensitive():
    """Same entities in different slots must hash identically — the reference
    checksum is a wrapping sum over entities, not a sequential digest
    (world_snapshot.rs:72-75)."""
    reg = make_registry()
    a = HostWorld(reg, 8)
    a.spawn({"translation": [1.0, 2.0, 3.0]}, rollback_id=7)
    a.spawn({"velocity": [4.0, 5.0, 6.0]}, rollback_id=9)
    b = HostWorld(reg, 8)
    b.spawn({"velocity": [4.0, 5.0, 6.0]}, rollback_id=9)
    b.spawn({"translation": [1.0, 2.0, 3.0]}, rollback_id=7)
    assert combine64(checksum(a.commit())) == combine64(checksum(b.commit()))


def test_checksum_ignores_dead_slot_garbage():
    """Stale component data in dead/non-present slots must not affect the
    checksum, or resimulated worlds with different spawn histories would
    falsely desync."""
    reg = make_registry()
    state = make_world(reg, 4).commit()
    dirty = state.replace(
        components={**state.components,
                    "translation": state.components["translation"].at[3].set(99.0)}
    )
    assert combine64(checksum(state)) == combine64(checksum(dirty))


def test_checksum_sees_resources():
    reg = make_registry()
    state = make_world(reg).commit()
    bumped = state.replace(resources={"frame_count": jnp.int32(5)})
    assert combine64(checksum(state)) != combine64(checksum(bumped))


def test_checksum_distinguishes_present_from_default():
    """An entity *with* a component at its default value differs from one
    *without* the component (insert vs. absent — world_snapshot.rs:154-184)."""
    reg = make_registry()
    a = HostWorld(reg, 4)
    a.spawn({"translation": [0.0, 0.0, 0.0]}, rollback_id=0)
    b = HostWorld(reg, 4)
    b.spawn({}, rollback_id=0)
    assert combine64(checksum(a.commit())) != combine64(checksum(b.commit()))


def test_ring_save_load_roundtrip():
    reg = make_registry()
    state = make_world(reg).commit()
    ring = ring_init(state, depth=4)
    ring, cs = ring_save(ring, state, 0)
    assert int(ring.frames[0]) == 0
    assert combine64(cs) == combine64(checksum(state))

    moved = state.replace(
        components={**state.components,
                    "translation": state.components["translation"] + 1.0}
    )
    ring, _ = ring_save(ring, moved, 1)

    back0 = ring_load(ring, 0)
    back1 = ring_load(ring, 1)
    np.testing.assert_array_equal(
        np.asarray(back0.components["translation"]),
        np.asarray(state.components["translation"]),
    )
    np.testing.assert_array_equal(
        np.asarray(back1.components["translation"]),
        np.asarray(moved.components["translation"]),
    )


def test_ring_wraparound_overwrites():
    """frame % depth indexing (ggrs_stage.rs:286,294): frame depth+k lands on
    slot k, overwriting the old snapshot."""
    reg = make_registry()
    state = make_world(reg).commit()
    ring = ring_init(state, depth=3)
    for f in range(5):
        bumped = state.replace(resources={"frame_count": jnp.int32(f)})
        ring, _ = ring_save(ring, bumped, f)
    np.testing.assert_array_equal(np.asarray(ring.frames), [3, 4, 2])
    assert int(ring_load(ring, 4).resources["frame_count"]) == 4


def test_restore_reconciles_spawn_despawn():
    """Entities created during mispredicted frames vanish on restore; entities
    destroyed during mispredicted frames come back — the reference walks
    spawn/despawn per entity (world_snapshot.rs:140-151,190-193); here the
    alive mask restore does it wholesale."""
    reg = make_registry()
    host = make_world(reg)
    state = host.commit()
    ring = ring_init(state, depth=4)
    ring, _ = ring_save(ring, state, 0)

    # Mispredicted future: entity 0 despawned, a new entity spawned in slot 2.
    mutated = state.replace(
        alive=state.alive.at[0].set(False).at[2].set(True),
        rollback_id=state.rollback_id.at[0].set(-1).at[2].set(77),
    )
    restored = ring_load(ring, 0)
    np.testing.assert_array_equal(np.asarray(restored.alive), np.asarray(state.alive))
    np.testing.assert_array_equal(
        np.asarray(restored.rollback_id), np.asarray(state.rollback_id)
    )
    assert combine64(checksum(restored)) == combine64(checksum(state))
    assert combine64(checksum(mutated)) != combine64(checksum(state))


def test_ring_ops_jittable():
    reg = make_registry()
    state = make_world(reg).commit()
    ring = ring_init(state, depth=4)

    @jax.jit
    def save_then_load(ring, state, frame):
        ring, cs = ring_save(ring, state, frame)
        return ring_load(ring, frame), cs

    back, cs = save_then_load(ring, state, jnp.int32(2))
    assert combine64(cs) == combine64(checksum(state))
    np.testing.assert_array_equal(np.asarray(back.alive), np.asarray(state.alive))


def test_empty_registry_state():
    reg = TypeRegistry()
    state = init_state(reg, 4)
    assert int(state.num_alive()) == 0
    combine64(checksum(state))  # must not crash on empty component/resource dicts


def test_checksum_breakdown_localizes_divergence():
    from bevy_ggrs_tpu.state import checksum_breakdown

    reg = make_registry()
    state = make_world(reg).commit()
    base = checksum_breakdown(state)
    assert set(k.split("/")[0] for k in base) >= {"component", "rollback_id", "alive"}

    # Mutate exactly one component: only its entry may change.
    name = sorted(state.components)[0]
    mutated = state.replace(
        components={
            **state.components,
            name: state.components[name] + jnp.ones_like(state.components[name]),
        }
    )
    mb = checksum_breakdown(mutated)
    diff = {k for k in base if mb[k] != base[k]}
    assert diff == {f"component/{name}"}

    # Mutate a resource: only that resource entry changes.
    rname = sorted(state.resources)[0]
    bumped = state.replace(
        resources={
            **state.resources,
            rname: jax.tree_util.tree_map(lambda x: x + 1, state.resources[rname]),
        }
    )
    bb = checksum_breakdown(bumped)
    diff_r = {k for k in base if bb[k] != base[k]}
    assert diff_r == {f"resource/{rname}"}


def test_runner_diagnose_frame():
    from bevy_ggrs_tpu.models import box_game
    from bevy_ggrs_tpu.runner import RollbackRunner
    from bevy_ggrs_tpu.session import SyncTestSession

    session = SyncTestSession(2, box_game.INPUT_SPEC, check_distance=2,
                              max_prediction=4)
    runner = RollbackRunner(box_game.make_schedule(),
                            box_game.make_world(2).commit(),
                            max_prediction=4, num_players=2,
                            input_spec=box_game.INPUT_SPEC)
    for i in range(6):
        for h in range(2):
            session.add_local_input(h, np.uint8(i % 4))
        runner.handle_requests(session.advance_frame(), session)
    d = runner.diagnose_frame(runner.frame - 1)
    assert d is not None and "component/translation" in d
    assert runner.diagnose_frame(runner.frame - 100) is None
