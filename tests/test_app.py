"""App layer: GGRSPlugin builder + GGRSStage fixed-timestep driver."""

import jax
import numpy as np
import pytest

from bevy_ggrs_tpu.app import GGRSPlugin, RollbackApp, SessionType
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.session import MismatchedChecksum, SessionBuilder, PlayerType
from bevy_ggrs_tpu.transport.loopback import LoopbackNetwork

import jax.numpy as jnp


def constant_input(key):
    return lambda handle, app: np.uint8(key)


def scripted(handle, app):
    keys = [box_game.INPUT_UP, box_game.INPUT_RIGHT, box_game.INPUT_DOWN, 0]
    frame = app.session.current_frame
    return np.uint8(keys[(frame // 3 + handle) % len(keys)])


def build_box_app(num_players=2, fps=60, input_fn=None, max_prediction=8,
                  clock=None, speculation=0, mesh=None):
    def setup(world, app):
        box_game.spawn_players(
            world, num_players, next_id=app.rollback_id_provider.next_id
        )

    plugin = (
        GGRSPlugin(box_game.INPUT_SPEC)
        .with_update_frequency(fps)
        .with_input_system(input_fn or constant_input(box_game.INPUT_UP))
        .register_rollback_component("translation", shape=(3,), dtype=jnp.float32)
        .register_rollback_component("velocity", shape=(3,), dtype=jnp.float32)
        .register_rollback_component("player_handle", dtype=jnp.int32, default=-1)
        .register_rollback_resource("frame_count", jnp.uint32(0))
        .with_rollback_schedule(box_game.make_schedule())
        .with_num_players(num_players)
        .with_max_prediction_window(max_prediction)
        .with_world_capacity(16)
        .with_setup_system(setup)
    )
    if clock is not None:
        plugin.with_clock(clock)
    if speculation:
        plugin.with_speculation(speculation)
    if mesh is not None:
        plugin.with_mesh(mesh)
    return plugin.build()


class TestBuilder:
    def test_requires_input_system(self):
        with pytest.raises(ValueError, match="input system"):
            GGRSPlugin().build()

    def test_setup_spawns_players(self):
        app = build_box_app(num_players=3)
        world = app.world()
        assert int(world["alive"].sum()) == 3
        assert sorted(world["rollback_id"][world["alive"]]) == [0, 1, 2]


class TestFixedTimestep:
    def test_accumulator_runs_zero_to_k_steps(self):
        app = build_box_app(fps=60)
        session = (
            SessionBuilder(box_game.INPUT_SPEC)
            .with_num_players(2)
            .with_check_distance(0)
            .start_synctest_session()
        )
        app.insert_session(session, SessionType.SYNC_TEST)
        dt = 1.0 / 60.0
        assert app.update(now=0.0) == 0  # first call only sets last_time
        assert app.update(now=0.5 * dt) == 0  # not enough accumulated
        assert app.update(now=1.6 * dt) == 1
        assert app.update(now=4.6 * dt) == 3  # catches up with 3 steps
        assert app.frame == 4

    def test_run_slow_stretches_period(self):
        app = build_box_app(fps=60)
        app.stage.run_slow = True
        session = (
            SessionBuilder(box_game.INPUT_SPEC)
            .with_num_players(2)
            .with_check_distance(0)
            .start_synctest_session()
        )
        app.insert_session(session, SessionType.SYNC_TEST)
        dt = 1.0 / 60.0
        app.update(now=0.0)
        # 1.05 normal periods < 1.1 stretched periods: no step yet.
        # SyncTest never sets run_slow, so it stays at the forced value.
        assert app.update(now=1.05 * dt) == 0
        assert app.update(now=1.2 * dt) == 1

    def test_reset_on_session_removal(self):
        app = build_box_app()
        session = (
            SessionBuilder(box_game.INPUT_SPEC)
            .with_num_players(2)
            .start_synctest_session()
        )
        app.insert_session(session, SessionType.SYNC_TEST)
        app.run_for(5, dt=1.0 / 60.0)
        assert app.stage.accumulator >= 0.0 and app.stage.last_time is not None
        app.remove_session()
        app.update(now=99.0)
        assert app.stage.last_time is None  # reset (`ggrs_stage.rs:155-161`)


class TestSyncTestApp:
    def test_synctest_green(self):
        app = build_box_app(input_fn=scripted)
        session = (
            SessionBuilder(box_game.INPUT_SPEC)
            .with_num_players(2)
            .with_check_distance(4)
            .start_synctest_session()
        )
        app.insert_session(session, SessionType.SYNC_TEST)
        app.run_for(30, dt=1.0 / 60.0)  # raises MismatchedChecksum on desync
        # First update only arms the clock, so 30 render frames yield ~29
        # sim steps (modulo float accumulation).
        assert app.frame >= 27
        assert app.stage.runner.rollbacks_total > 0


class TestP2PApp:
    def _run_two_apps(self, speculation=0, mesh=None):
        net = LoopbackNetwork(latency=2 / 60.0)
        apps = []
        for me in range(2):
            clock = lambda: net.now
            app = build_box_app(input_fn=scripted, clock=clock,
                                max_prediction=8,
                                speculation=speculation if me == 0 else 0,
                                mesh=mesh)
            builder = (
                SessionBuilder(box_game.INPUT_SPEC)
                .with_num_players(2)
                .with_max_prediction_window(8)
            )
            for h in range(2):
                builder.add_player(
                    PlayerType.local() if h == me else PlayerType.remote(("peer", h)),
                    h,
                )
            session = builder.start_p2p_session(
                net.socket(("peer", me)), clock=clock
            )
            app.insert_session(session, SessionType.P2P)
            apps.append(app)

        dt = 1.0 / 60.0
        for i in range(90):
            net.advance(dt)
            for app in apps:
                app.update(now=net.now)

        a, b = apps
        assert a.frame > 40 and b.frame > 40
        assert a.stage.runner.rollbacks_total > 0
        sa, sb = a.session, b.session
        upto = min(sa.confirmed_frame(), sb.confirmed_frame())
        common = [
            f for f in sa._local_checksums
            if f <= upto and f in sb._local_checksums
        ]
        # Lazy checksum reporting: only desync-interval frames are synced
        # to the host and stored (wants_checksum) — all of them must agree.
        assert sa.desync_interval == min(16, sa.max_prediction)  # auto
        assert len(common) >= 2
        assert all(f % sa.desync_interval == 0 for f in common)
        assert all(sa._local_checksums[f] == sb._local_checksums[f] for f in common)
        return apps

    def test_two_apps_over_loopback(self):
        self._run_two_apps()

    def test_two_apps_with_speculation_stay_consistent(self):
        """GGRSStage wiring of with_speculation: app A speculates (stage
        calls runner.speculate with the session each tick), app B runs
        serial — the interval checksums must still agree bitwise, and the
        speculative runner must actually engage."""
        apps = self._run_two_apps(speculation=16)
        runner = apps[0].stage.runner
        assert hasattr(runner, "spec_hits")
        assert runner.rollbacks_total > 0
        # The structured tree + pinning should recover at least something
        # over 90 frames of every-3-frame input changes at 2-frame latency.
        assert runner.spec_hits + runner.spec_partial_hits > 0


class TestMeshedApp:
    def test_with_mesh_shards_session_and_speculation(self):
        """GGRSPlugin.with_mesh threads the mesh through GGRSStage into the
        runner: world entity-sharded, live speculative rollouts branch-
        sharded — and the meshed pair stays bitwise-consistent end to end
        (same helper and assertions as the unmeshed P2P tests)."""
        from bevy_ggrs_tpu.parallel.sharding import branch_mesh

        if len(jax.devices()) < 4:
            pytest.skip("needs a 2D mesh")
        mesh = branch_mesh(entity_shards=2)  # branches x entity
        apps = TestP2PApp()._run_two_apps(speculation=8, mesh=mesh)
        runner = apps[0].stage.runner
        assert not runner.state.components[
            "translation"
        ].sharding.is_fully_replicated
        # Live speculation really ran sharded over the mesh's branch axis.
        assert runner._result is not None
        assert not runner._result.checksums.sharding.is_fully_replicated
