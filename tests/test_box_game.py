"""box_game step engine tests: physics semantics + JAX↔NumPy bit-exactness.

The determinism contract is the survey's §4: simulate vs. resimulate (and
JAX vs. the NumPy oracle) must agree bitwise, because rollback correctness
rests on reproducible checksums (reference ``examples/README.md:13-18``).
"""

import jax
import jax.numpy as jnp
import numpy as np

from bevy_ggrs_tpu import checksum, combine64, to_host
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.schedule import make_inputs


def commit(num_players=2, capacity=16):
    return box_game.make_world(num_players, capacity).commit()


def test_idle_players_decelerate():
    state = commit()
    sched = box_game.make_schedule()
    moving = state.replace(
        components={**state.components,
                    "velocity": state.components["velocity"].at[0].set(
                        jnp.array([0.04, 0.0, 0.0]))}
    )
    out = sched(moving, make_inputs(np.zeros(2, np.uint8)))
    v = np.asarray(out.components["velocity"][0])
    np.testing.assert_allclose(v[0], 0.04 * 0.9, rtol=1e-6)


def test_input_accelerates_only_owner():
    state = commit()
    sched = box_game.make_schedule()
    out = sched(state, make_inputs(np.array([box_game.INPUT_UP, 0], np.uint8)))
    v = np.asarray(out.components["velocity"])
    assert v[0, 2] < 0  # UP = -z (box_game.rs:162-163)
    assert v[1, 2] == 0.0


def test_opposing_keys_cancel():
    state = commit()
    sched = box_game.make_schedule()
    bits = np.array([box_game.INPUT_UP | box_game.INPUT_DOWN, 0], np.uint8)
    out = sched(state, make_inputs(bits))
    # Both pressed: no accel AND no friction on that axis (box_game.rs:161-166).
    np.testing.assert_array_equal(np.asarray(out.components["velocity"][0]),
                                  np.zeros(3, np.float32))


def test_speed_clamp():
    state = commit()
    sched = box_game.make_schedule()
    bits = np.array([box_game.INPUT_UP | box_game.INPUT_LEFT, 0], np.uint8)
    for _ in range(60):
        state = sched(state, make_inputs(bits))
    speed = float(jnp.linalg.norm(state.components["velocity"][0]))
    assert speed <= box_game.MAX_SPEED + 1e-6


def test_plane_clamp():
    state = commit()
    sched = box_game.make_schedule()
    bits = np.array([box_game.INPUT_RIGHT, 0], np.uint8)
    for _ in range(400):
        state = sched(state, make_inputs(bits))
    x = float(state.components["translation"][0, 0])
    assert abs(x - (box_game.PLANE_SIZE - box_game.CUBE_SIZE) * 0.5) < 1e-6


def test_frame_count_increments():
    state = commit()
    sched = box_game.make_schedule()
    out = sched(sched(state, make_inputs(np.zeros(2, np.uint8))),
                make_inputs(np.zeros(2, np.uint8)))
    assert int(out.resources["frame_count"]) == 2


def test_dead_and_nonplayer_slots_untouched():
    state = commit(2, 8)
    dirty = state.replace(
        components={**state.components,
                    "translation": state.components["translation"].at[5].set(3.0)}
    )
    out = box_game.make_schedule()(dirty, make_inputs(
        np.array([box_game.INPUT_UP, box_game.INPUT_DOWN], np.uint8)))
    np.testing.assert_array_equal(np.asarray(out.components["translation"][5]),
                                  np.full(3, 3.0, np.float32))


def _assert_ulp_close(got: np.ndarray, want: np.ndarray, max_ulp: int = 16):
    diff = np.abs(
        got.view(np.int32).astype(np.int64) - want.view(np.int32).astype(np.int64)
    )
    assert diff.max() <= max_ulp, f"max ulp diff {diff.max()}"


def test_jax_matches_numpy_oracle():
    """100 frames of pseudo-random inputs: JAX step must track the NumPy twin
    to within FMA-contraction noise (≤2 ulp — XLA contracts mul+add chains in
    the speed clamp). Exact cross-platform float equality is explicitly NOT
    the contract — the reference documents float desync across architectures
    as expected (`examples/README.md:13-18`); the hard bitwise property is
    same-platform reproducibility (next test)."""
    state = commit(4)
    sched = box_game.make_schedule()
    host = to_host(state)
    rng = np.random.RandomState(7)
    jit_sched = jax.jit(sched)
    for _ in range(100):
        bits = rng.randint(0, 16, size=4).astype(np.uint8)
        state = jit_sched(state, make_inputs(bits))
        host = box_game.step_np(host, bits)
    _assert_ulp_close(np.asarray(state.components["translation"]),
                      host["components"]["translation"])
    _assert_ulp_close(np.asarray(state.components["velocity"]),
                      host["components"]["velocity"])
    assert int(state.resources["frame_count"]) == int(host["resources"]["frame_count"])


def test_resimulation_checksum_reproducible():
    """Same start state + same inputs ⇒ identical checksum after N frames —
    the property SyncTest enforces every frame."""
    state = commit(2)
    sched = jax.jit(box_game.make_schedule())
    rng = np.random.RandomState(3)
    seq = [rng.randint(0, 16, size=2).astype(np.uint8) for _ in range(20)]
    a = state
    for bits in seq:
        a = sched(a, make_inputs(bits))
    b = state
    for bits in seq:
        b = sched(b, make_inputs(bits))
    assert combine64(checksum(a)) == combine64(checksum(b))
