"""Speculation ledger (obs/ledger.py): single source of truth, proven.

- **Reconciliation**: the ledger's per-rollback entries must sum exactly
  to the legacy aggregate counters (``spec_hits`` / ``spec_partial_hits``
  / ``spec_misses`` / ``rollbacks_total`` /
  ``rollback_frames_recovered_total``) over a paced chaos pair AND an
  S=16 batched soak — no second source of truth allowed to drift.
- **Blame flow arrows**: a blamed entry exported as provenance must link
  the blamed input datagram's flow key to a terminal ``spec_resim`` hop
  in the merged fleet timeline, crossing process tracks.
- **Recorder depth fix**: a capture window spanning multiple rollbacks
  must report the MAX per-rollback depth (from the ledger), not the sum;
  single-rollback captures stay bitwise, and the no-ledger fallback
  keeps the old summed column.
- **Counterfactual harness**: the offline ranking replay must score the
  current heuristic against the repeat-last ablation and never invert
  them.
"""

import json

import numpy as np

from bevy_ggrs_tpu.chaos import ChaosPlan, ChaosSocket
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.obs import FlightRecorder, ProvenanceLog, SidecarSocket
from bevy_ggrs_tpu.obs.ledger import (
    POLICIES,
    SpeculationLedger,
    blame_divergence,
    null_ledger,
    replay_baseline,
)
from bevy_ggrs_tpu.obs.merge import merge_traces
from bevy_ggrs_tpu.runner import RollbackRunner
from bevy_ggrs_tpu.session import (
    PlayerType,
    PredictionThreshold,
    SessionBuilder,
    SessionState,
)
from bevy_ggrs_tpu.session.protocol import FleetHeartbeat, decode, encode
from bevy_ggrs_tpu.spec_runner import SpeculativeRollbackRunner
from bevy_ggrs_tpu.transport.loopback import LoopbackNetwork
from tests.test_batched_sessions import drive, make_core, make_script
from tests.test_p2p import FPS_DT, scripted_input


def run_spec_pair(ledger, provenance=False, frames=240):
    """Paced chaos pair: peer 0 speculates (B=16, F=8) with ``ledger``,
    peer 1 runs plain. Returns (peers, {peer: ProvenanceLog})."""
    net = LoopbackNetwork()
    plan = ChaosPlan.generate(11, 3.0, (("peer", 0), ("peer", 1)))
    prov = {}
    peers = []
    for me in range(2):
        sock = net.socket(("peer", me))
        if provenance:
            prov[me] = ProvenanceLog(
                f"peer{me}", pid=me, clock=lambda: net.now
            )
            sock = SidecarSocket(sock, prov[me])
        sock = ChaosSocket(
            sock, plan, clock=lambda: net.now, addr=("peer", me)
        )
        builder = (
            SessionBuilder(box_game.INPUT_SPEC)
            .with_num_players(2)
            .with_max_prediction_window(8)
        )
        for h in range(2):
            builder.add_player(
                PlayerType.local() if h == me
                else PlayerType.remote(("peer", h)), h,
            )
        session = builder.start_p2p_session(sock, clock=lambda: net.now)
        if me == 0:
            runner = SpeculativeRollbackRunner(
                box_game.make_schedule(), box_game.make_world(2).commit(),
                max_prediction=8, num_players=2,
                input_spec=box_game.INPUT_SPEC,
                num_branches=16, spec_frames=8, ledger=ledger,
            )
        else:
            runner = RollbackRunner(
                box_game.make_schedule(), box_game.make_world(2).commit(),
                max_prediction=8, num_players=2,
                input_spec=box_game.INPUT_SPEC,
            )
        peers.append((session, runner))
    for _ in range(frames):
        net.advance(FPS_DT)
        for session, runner in peers:
            session.poll_remote_clients()
            if session.current_state() != SessionState.RUNNING:
                continue
            for h in session.local_player_handles():
                session.add_local_input(
                    h, scripted_input(h, session.current_frame)
                )
            try:
                requests = session.advance_frame()
            except PredictionThreshold:
                continue
            runner.handle_requests(requests, session)
            if isinstance(runner, SpeculativeRollbackRunner):
                runner.speculate(session.confirmed_frame(), session)
    return peers, prov


def assert_reconciled(ledger, counters):
    """Ledger totals == legacy counters, exactly."""
    s = ledger.summary()
    assert s["spec_full"] == counters.spec_hits
    assert s["spec_partial"] == counters.spec_partial_hits
    assert s["spec_miss"] == counters.spec_misses
    assert s["rollbacks"] == counters.rollbacks_total
    assert (
        s["spec_full"] + s["spec_partial"] + s["spec_miss"]
        + s["spec_unmatched"] == counters.rollbacks_total
    )
    assert (
        s["frames_recovered_total"]
        == counters.rollback_frames_recovered_total
    )
    for e in ledger.entries:
        assert e["frames_recovered"] + e["frames_resimulated"] == e["depth"]


class TestReconciliation:
    def test_paced_chaos_pair(self):
        ledger = SpeculationLedger()
        peers, _ = run_spec_pair(ledger)
        r0 = peers[0][1]
        assert r0.rollbacks_total > 0, "chaos pair produced no rollbacks"
        assert r0.spec_hits + r0.spec_partial_hits > 0, (
            "speculation never engaged"
        )
        assert_reconciled(ledger, r0)
        # Economics present: every hit carries its branch rank, the
        # rollout accounting saw the B×F dispatches.
        assert ledger.rollouts_dispatched > 0
        assert ledger.spec_frames_dispatched == (
            16 * 8 * ledger.rollouts_dispatched
        )
        for e in ledger.entries:
            if e["outcome"] in ("full", "partial"):
                assert 0 <= e["rank"] < 16

    def test_batched_s16_soak(self):
        ledger = SpeculationLedger()
        core = make_core(num_slots=16, ledger=ledger)
        slots = [core.admit() for _ in range(16)]
        scripts = {
            s: make_script(seed=500 + s, depth=1 + (s % 4), cycles=3)
            for s in slots
        }
        drive(core, scripts)
        assert core.rollbacks_total > 0
        assert_reconciled(ledger, core)
        # Entries carry their flat slot id.
        assert {e.get("slot") for e in ledger.entries} <= set(slots)


class TestBlame:
    def test_blame_divergence_picks_first_frame_major(self):
        pred = np.zeros((4, 2), np.uint8)
        corr = pred.copy()
        corr[2, 1] = 5  # first divergence: frame offset 2, player 1
        corr[3, 0] = 7
        assert blame_divergence(pred, corr) == (2, 1)
        assert blame_divergence(pred, pred) is None

    def test_chaos_pair_attributes_remote_player(self):
        """Peer 0's misprediction can only come from the remote player
        (its own inputs are never predicted), so every blamed entry must
        name player 1."""
        ledger = SpeculationLedger()
        peers, _ = run_spec_pair(ledger)
        blamed = [
            e for e in ledger.entries if e.get("blame_player") is not None
        ]
        assert blamed, "no rollback produced a blame attribution"
        assert {e["blame_player"] for e in blamed} == {1}
        s = ledger.summary()
        assert s["blame_top_player_share"] == 1.0

    def test_flow_arrow_crosses_process_tracks(self, tmp_path):
        """The blamed input datagram's provenance flow key must chain
        sender-tx → receiver-rx → terminal spec_resim across distinct
        process tracks in the merged trace."""
        ledger = SpeculationLedger(component="spec-ledger", pid=0)
        peers, prov = run_spec_pair(ledger, provenance=True)
        p0 = tmp_path / "peer0_prov.jsonl"
        p1 = tmp_path / "peer1_prov.jsonl"
        pl = tmp_path / "ledger_prov.jsonl"
        prov[0].export_jsonl(str(p0))
        prov[1].export_jsonl(str(p1))
        written = ledger.export_provenance(str(pl), prov[0])
        assert written > 0, "no blamed entry resolved an input datagram"
        merged = tmp_path / "merged.json"
        trace = merge_traces(
            [], [str(p0), str(p1), str(pl)], path=str(merged)
        )
        flows = {}
        for ev in trace["traceEvents"]:
            if ev.get("cat") == "flow":
                flows.setdefault(ev["id"], []).append(ev)
        spec_flows = [
            hops for hops in flows.values()
            if any(h["name"] == "spec_resim" for h in hops)
        ]
        assert spec_flows, "no flow chain reached a spec_resim hop"
        found = False
        for hops in spec_flows:
            pids = {h["pid"] for h in hops}
            terminal = hops[-1]
            if len(pids) >= 2 and terminal["name"] == "spec_resim":
                assert terminal["ph"] == "f", (
                    "spec_resim hop must terminate its flow"
                )
                found = True
        assert found, (
            "no blamed-input flow crossed process tracks into spec_resim"
        )


class _FakeRunner:
    def __init__(self, ledger=None):
        self.frame = 0
        self.rollbacks_total = 0
        self.rollback_frames_total = 0
        if ledger is not None:
            self.ledger = ledger


class TestRecorderDepth:
    def test_multi_rollback_capture_reports_max_not_sum(self):
        ledger = SpeculationLedger()
        runner = _FakeRunner(ledger)
        rec = FlightRecorder()
        rec.capture(runner=runner)  # prime the delta baselines
        # Two rollbacks (depths 2 and 3) land inside ONE capture window:
        # the old column conflated them into a single depth-5 rollback.
        runner.rollbacks_total += 2
        runner.rollback_frames_total += 5
        ledger.record("miss", depth=2, frames_resimulated=2, load_frame=10)
        ledger.record("miss", depth=3, frames_resimulated=3, load_frame=14)
        r = rec.capture(runner=runner)
        assert r.rollbacks == 2 and r.resim_frames == 5
        assert r.rollback_depth == 3

    def test_single_rollback_capture_stays_bitwise(self):
        ledger = SpeculationLedger()
        runner = _FakeRunner(ledger)
        rec = FlightRecorder()
        rec.capture(runner=runner)
        runner.rollbacks_total += 1
        runner.rollback_frames_total += 4
        ledger.record("miss", depth=4, frames_resimulated=4, load_frame=3)
        r = rec.capture(runner=runner)
        assert r.rollback_depth == 4  # == the old resim-delta value

    def test_no_ledger_fallback_keeps_summed_column(self):
        runner = _FakeRunner()  # no ledger attr at all
        rec = FlightRecorder()
        rec.capture(runner=runner)
        runner.rollbacks_total += 2
        runner.rollback_frames_total += 5
        r = rec.capture(runner=runner)
        assert r.rollback_depth == 5  # legacy summed behavior


class TestLedgerUnits:
    def test_scoped_view_offsets_slots_into_parent(self):
        parent = SpeculationLedger()
        g1 = parent.scoped(8)
        g1.record("full", depth=2, frames_recovered=2, rank=0, slot=3)
        g1.record_rollout(64, slot=3)
        assert parent.entries[-1]["slot"] == 11
        assert parent.rollbacks == 1
        assert parent.spec_frames_dispatched == 64

    def test_null_ledger_is_inert_and_self_scoping(self):
        assert null_ledger.enabled is False
        assert null_ledger.scoped(4) is null_ledger
        null_ledger.record("full", depth=1)
        null_ledger.record_rollout(100)
        assert null_ledger.rollbacks == 0
        assert null_ledger.tail(0) == []
        assert null_ledger.summary() == {}

    def test_tail_is_incremental(self):
        led = SpeculationLedger()
        led.record("miss", depth=1, frames_resimulated=1)
        led.record("full", depth=2, frames_recovered=2, rank=1)
        first = led.tail(0)
        assert [e["seq"] for e in first] == [0, 1]
        assert led.tail(first[-1]["seq"] + 1) == []
        led.record("partial", depth=3, frames_recovered=1,
                   frames_resimulated=2, rank=0)
        assert [e["seq"] for e in led.tail(2)] == [2]

    def test_export_jsonl_roundtrips(self, tmp_path):
        led = SpeculationLedger()
        led.record("full", depth=2, frames_recovered=2, branch=1, rank=1,
                   blame_player=0, blame_frame=5, slot=2, load_frame=4)
        p = tmp_path / "ledger.jsonl"
        led.export_jsonl(str(p))
        lines = [json.loads(x) for x in p.read_text().splitlines()]
        assert lines[0]["meta"]["summary"]["spec_full"] == 1
        assert lines[1]["outcome"] == "full"
        assert lines[1]["blame_player"] == 0


class TestFleetHeartbeatSpecFields:
    def test_roundtrip_with_spec_rollup(self):
        hb = FleetHeartbeat(
            3, 999, 4, 2, 1, 0,
            spec_hit_permille=750, spec_waste_permille=990,
        )
        assert decode(encode(hb)) == hb

    def test_legacy_positional_construction_defaults_to_zero(self):
        hb = FleetHeartbeat(3, 999, 4, 2, 1, 0)
        out = decode(encode(hb))
        assert out.spec_hit_permille == 0
        assert out.spec_waste_permille == 0


class TestCounterfactualHarness:
    def test_replay_scores_policies_without_inversion(self):
        out = replay_baseline(frames=72, configs=["box_game"])
        assert set(out["policies"]) == set(POLICIES)
        cfg = out["configs"]["box_game"]
        assert cfg["players"] == 2
        pol = cfg["policies"]
        assert set(pol) == set(POLICIES)
        for p in pol.values():
            assert p["anchors"] > 0
            assert 0.0 <= p["full_hit_rate"] <= 1.0
            assert 0.0 <= p["waste_ratio"] <= 1.0
        # The shipped heuristic (recency + periodic extrapolation) must
        # never lose to its own repeat-last-only ablation — that ordering
        # IS the baseline the learned predictor must beat.
        assert (
            pol["current"]["full_hit_rate"]
            >= pol["repeat_last"]["full_hit_rate"]
        )
