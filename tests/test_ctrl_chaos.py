"""Chaos-hardened control plane: reliable migration wire, split-brain
fencing, partition-aware liveness and autopilot degradation.

Four layers of the robustness story, bottom-up:

- :class:`~bevy_ggrs_tpu.transport.reliable.ReliableSocket` turns the
  UDP control wire into at-least-once + idempotent delivery for the
  migration family (types 18-21) while heartbeats stay fire-and-forget.
- Migration epochs (fencing tokens) make stale/duplicated landings
  structurally refusable: every refusal is typed, aborts resolve without
  resurrecting a superseded copy, and ``matches_lost`` stays zero.
- Heartbeat liveness survives reorder: only monotonically newer
  ``beat_seq`` values refresh a member, and death is K missed beats —
  a late stale burst cannot mask real silence.
- The autopilot distinguishes "server dead" from "network suspect"
  (missed beats + control-plane probe) and freezes shrink-side actions
  while degraded; the degraded decisions replay bit-identically.

The slow soak at the bottom drives the full N=3 elasticity arc
(scale-up -> preempt -> pack -> retire) over subprocess MatchServers
whose real UDP sockets are wrapped in a ChaosSocket running loss,
duplication, corruption, reorder, and an asymmetric partition — and
demands the same zero-loss, zero-churn, replay-identical outcome the
calm soak gets.
"""

import os
import time

import pytest

from bevy_ggrs_tpu.chaos import ChaosPlan
from bevy_ggrs_tpu.chaos.plan import (
    Corrupt,
    Duplicate,
    LossBurst,
    Partition,
    Reorder,
)
from bevy_ggrs_tpu.fleet import FleetBalancer
from bevy_ggrs_tpu.fleet.autopilot import (
    AutopilotConfig,
    AutopilotPolicy,
    FleetObservation,
    ServerSample,
    _action_to_json,
    observation_from_json,
    observation_to_json,
    replay_ledger,
)
from bevy_ggrs_tpu.session import protocol as proto
from bevy_ggrs_tpu.transport.loopback import LoopbackNetwork
from bevy_ggrs_tpu.transport.reliable import ReliableSocket
from bevy_ggrs_tpu.utils.metrics import Metrics
from tests.test_fleet import make_migration_fleet
from tests.test_serve_faults import inputs_for, make_server, make_synctest


# ---------------------------------------------------------------------------
# Wire additions: epochs, refusal reasons, beat_seq, ctrl envelopes
# ---------------------------------------------------------------------------


def test_control_wire_fields_roundtrip():
    msgs = [
        proto.MigrateOffer(7, 3, 120, 5, 0xDEAD, 9),
        proto.MigrateAccept(7, False, 9, proto.MIG_REFUSE_EPOCH),
        proto.MigrateChunk(7, 120, 2, 5, 0xA1B2, b"payload", 9),
        proto.MigrateDone(7, 120, True, 9),
        proto.FleetHeartbeat(2, 600, 10, 6, 1, 0, beat_seq=41),
        proto.CtrlFrame(3, 0xFEEDFACE, b"inner-bytes"),
        proto.CtrlAck(3),
    ]
    for msg in msgs:
        back = proto.decode(proto.encode(msg))
        assert type(back) is type(msg)
        for f in msg.__dataclass_fields__:
            got, want = getattr(back, f), getattr(msg, f)
            if isinstance(want, bool):
                assert bool(got) == want, (msg, f)
            else:
                assert got == want, (msg, f)


def test_provenance_classifies_through_ctrl_envelope():
    """A tap above OR below the reliable sublayer attributes the inner
    migration frame identically — the envelope is transport plumbing."""
    from bevy_ggrs_tpu.obs.provenance import _classify

    inner = proto.encode(proto.MigrateChunk(1, 77, 0, 2, 3, b"x", 4))
    env = proto.encode(proto.CtrlFrame(9, 0, inner))
    assert _classify(env) == _classify(inner) == ("migrate_chunk", 77, None)
    assert _classify(proto.encode(proto.CtrlAck(9)))[0] == "ctrl_ack"


# ---------------------------------------------------------------------------
# ReliableSocket: at-least-once + idempotent over a scripted faulty wire
# ---------------------------------------------------------------------------


class _FaultyNet:
    """In-memory duplex with a scripted per-send verdict queue:
    'ok' | 'drop' | 'dup' | 'corrupt' (exhausted script means 'ok')."""

    def __init__(self, script=()):
        self.script = list(script)
        self.inbox = {"a": [], "b": []}

    def end(self, name):
        return _FaultyEnd(self, name)


class _FaultyEnd:
    def __init__(self, net, name):
        self.net, self.name = net, name

    def send_to(self, data, addr):
        verdict = self.net.script.pop(0) if self.net.script else "ok"
        if verdict == "drop":
            return
        if verdict == "corrupt":
            buf = bytearray(data)
            buf[len(buf) // 2] ^= 0x40
            data = bytes(buf)
        self.net.inbox[addr].append((self.name, bytes(data)))
        if verdict == "dup":
            self.net.inbox[addr].append((self.name, bytes(data)))

    def receive_all(self):
        out, self.net.inbox[self.name] = self.net.inbox[self.name], []
        return out

    def close(self):
        pass


OFFER = proto.encode(proto.MigrateOffer(1, 5, 10, 1, 0xABC, 1))
BEAT = proto.encode(proto.FleetHeartbeat(0, 1, 2, 3, 0, 0, beat_seq=1))


def _pair(script=(), **kw):
    net = _FaultyNet(script)
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    a = ReliableSocket(net.end("a"), clock=clock, seed=1, **kw)
    b = ReliableSocket(net.end("b"), clock=clock, seed=2, **kw)
    return a, b, t


def test_reliable_retransmits_lost_frame():
    a, b, t = _pair(script=["drop"])
    a.send_to(OFFER, "b")
    assert b.receive_all() == [] and a.pending_count == 1
    t[0] += 1.0  # past the RTO: the sender's pump retransmits
    a.pump()
    got = b.receive_all()
    assert [data for _, data in got] == [OFFER]
    assert a.retransmits == 1
    a.receive_all()  # drain b's ack
    assert a.pending_count == 0 and a.acked == 1


def test_reliable_dedups_duplicates():
    a, b, _ = _pair(script=["dup"])
    a.send_to(OFFER, "b")
    got = b.receive_all()
    assert [data for _, data in got] == [OFFER]  # delivered exactly once
    assert b.duplicates_dropped == 1
    a.receive_all()
    assert a.pending_count == 0  # both copies acked; either clears it


def test_reliable_drops_corrupt_and_recovers():
    a, b, t = _pair(script=["corrupt"])
    a.send_to(OFFER, "b")
    assert b.receive_all() == [] and b.crc_drops == 1
    t[0] += 1.0
    a.pump()
    got = b.receive_all()
    assert [data for _, data in got] == [OFFER]


def test_reliable_gives_up_after_max_retries():
    a, _b, t = _pair(script=["drop"] * 99, max_retries=3)
    a.send_to(OFFER, "b")
    for _ in range(10):
        t[0] += 5.0
        a.pump()
    assert a.gave_up == 1 and a.pending_count == 0
    assert a.retransmits == 3


def test_reliable_passthrough_for_heartbeats():
    a, b, _ = _pair()
    a.send_to(BEAT, "b")
    got = b.receive_all()
    assert [data for _, data in got] == [BEAT]  # unenveloped, verbatim
    assert a.pending_count == 0  # fire-and-forget: nothing to retransmit


def test_reliable_out_of_order_delivery_once_each():
    a, b, _ = _pair()
    frames = [
        proto.encode(proto.MigrateChunk(1, 10, seq, 3, 0, b"x", 1))
        for seq in range(3)
    ]
    for f in frames:
        a.send_to(f, "b")
    # Reorder in flight: reverse b's inbox.
    b.inner.net.inbox["b"].reverse()
    got = [data for _, data in b.receive_all()]
    assert sorted(got, key=frames.index) == frames
    # Replay the whole burst raw (stale seqs below the floor): all dropped.
    for f in frames:
        a.send_to(f, "b")  # new seqs — deliver fine
    assert len(b.receive_all()) == 3
    assert b.duplicates_dropped == 0


# ---------------------------------------------------------------------------
# Epoch fencing + corrupted/truncated/duplicated frame discipline
# ---------------------------------------------------------------------------


def test_stale_epoch_landing_refused_without_readmit():
    """A superseded migration attempt must not resolve anywhere: the
    fence refuses the landing AND refuses to resurrect the stale ticket
    at the source — either would double-host the match."""
    net = LoopbackNetwork()
    bal = make_migration_fleet(net)
    bal.place_match(0, make_synctest(), inputs_for(7), server_id=0)
    srv0 = bal.members[0].server
    for _ in range(4):
        srv0.run_frame()

    mig = bal.begin_migration(0, dst_id=1)
    active_before = srv0.slots_active
    # A newer attempt (e.g. a failover initiated while this one looked
    # wedged) bumps the match's fence past this attempt's token.
    bal._epochs[0] += 1
    net.advance(0.0)
    assert bal.complete_migration(mig) is None
    assert mig.resolved and mig.aborted and mig.dst_handle is None
    assert bal.epoch_fence_refusals == 1
    assert bal.abort_reasons.get("epoch_fence") == 1
    assert bal.metrics.counters.get("fleet_epoch_fence_refusals") == 1
    # Refusal is NOT an ordinary abort: the source slot stays drained.
    assert srv0.slots_active == active_before
    assert bal.matches_lost == 0


def test_corrupt_truncated_duplicate_frames_abort_typed():
    """Satellite: every tampered type 18-21 frame resolves backward with
    a typed reason and zero lost matches; truncated frames are inert;
    duplicated completions are idempotent."""
    net = LoopbackNetwork()
    bal = make_migration_fleet(net)
    bal.place_match(0, make_synctest(), inputs_for(7), server_id=0)
    srv0 = bal.members[0].server
    for _ in range(4):
        srv0.run_frame()
    original = bal.placements[0].handle
    evil = net.socket(("evil", 0))

    # (a) corrupted chunk (bad CRC) -> typed abort back to source slot.
    mig = bal.begin_migration(0, dst_id=1)
    evil.send_to(
        proto.encode(
            proto.MigrateChunk(
                mig.nonce, mig.frame, 0, mig.total, 0xBAD0BAD, b"junk",
                mig.epoch,
            )
        ),
        ("mig", 1),
    )
    net.advance(0.0)
    assert bal.complete_migration(mig) is None and mig.aborted
    assert bal.abort_reasons.get("chunk_crc") == 1
    assert bal.placements[0].server_id == 0
    assert bal.placements[0].handle == original

    # (b) truncated frame: decodes to None, changes nothing — the real
    # transfer completes around it.
    mig = bal.begin_migration(0, dst_id=1)
    evil.send_to(
        proto.encode(
            proto.MigrateDone(mig.nonce, mig.frame, 1, mig.epoch)
        )[:4],
        ("mig", 1),
    )
    net.advance(0.0)
    handle = bal.complete_migration(mig)
    assert handle is not None and not mig.aborted

    # (c) duplicated MigrateDone after resolution: idempotent, no
    # double-admit, counters unchanged.
    evil.send_to(
        proto.encode(proto.MigrateDone(mig.nonce, mig.frame, 1, mig.epoch)),
        ("mig", 1),
    )
    net.advance(0.0)
    assert bal.complete_migration(mig) == handle
    assert bal.migrations_completed == 1
    assert bal.matches_lost == 0


# ---------------------------------------------------------------------------
# Heartbeat liveness under reorder: beat_seq monotonicity + missed beats
# ---------------------------------------------------------------------------


def test_reordered_stale_heartbeat_cannot_mask_silence():
    net = LoopbackNetwork()
    bal = FleetBalancer(
        socket=net.socket(("fleet", "bal")),
        addr=("fleet", "bal"),
        heartbeat_timeout=0.9,
        dead_beats=3,
        clock=lambda: net.now,
        metrics=Metrics(),
    )
    bal.register(0, make_server(), addr=("mig", 0),
                 sock=net.socket(("mig", 0)))
    hb = net.socket(("hb", 0))

    def beat(seq):
        hb.send_to(
            proto.encode(
                proto.FleetHeartbeat(0, 10, 1, 3, 0, 0, beat_seq=seq)
            ),
            ("fleet", "bal"),
        )
        net.advance(0.0)
        bal.pump()

    beat(5)
    m = bal.members[0]
    assert m.last_beat_seq == 5 and m.missed_beats == 0
    net.advance(0.62)  # two beat periods (0.3 each) of real silence
    assert bal.check() == []
    assert m.missed_beats == 2 and m.alive
    # A REORDERED stale beat (seq < last seen) arrives late: it must not
    # refresh liveness.
    beat(3)
    assert bal.check() == []
    assert m.missed_beats == 2
    assert bal.metrics.counters.get("fleet_heartbeats_stale") == 1
    # Real silence continues to the third missed beat: dead.
    net.advance(0.4)
    assert bal.check() == [0]
    assert not m.alive


def test_corrupted_beat_seq_cannot_poison_liveness():
    """Heartbeats travel unenveloped, so a corrupted datagram that slips
    the header check can carry beat_seq with a high bit flipped. With a
    bare monotonic guard that single beat would raise the floor to ~2^31
    and every later genuine beat would read as stale — a live server
    permanently 'silent'. The bounded reorder window self-heals: the
    next genuine beat is far outside the window and resets the floor."""
    net = LoopbackNetwork()
    bal = FleetBalancer(
        socket=net.socket(("fleet", "bal")),
        addr=("fleet", "bal"),
        heartbeat_timeout=0.9,
        dead_beats=3,
        clock=lambda: net.now,
        metrics=Metrics(),
    )
    bal.register(0, make_server(), addr=("mig", 0),
                 sock=net.socket(("mig", 0)))
    hb = net.socket(("hb", 0))

    def beat(seq):
        hb.send_to(
            proto.encode(
                proto.FleetHeartbeat(0, 10, 1, 3, 0, 0, beat_seq=seq)
            ),
            ("fleet", "bal"),
        )
        net.advance(0.0)
        bal.pump()

    m = bal.members[0]
    beat(5)
    beat(5 | (1 << 31))  # the corrupted beat poisons the floor...
    beat(6)              # ...and the next genuine beat resets it
    assert m.last_beat_seq == 6
    net.advance(0.3)
    beat(7)
    assert m.missed_beats == 0 and m.alive
    # The window still rejects genuinely reordered duplicates.
    beat(6)
    assert m.last_beat_seq == 7
    assert bal.metrics.counters.get("fleet_heartbeats_stale") == 1


def test_fresh_heartbeat_resets_missed_beats():
    net = LoopbackNetwork()
    bal = FleetBalancer(
        socket=net.socket(("fleet", "bal")),
        addr=("fleet", "bal"),
        heartbeat_timeout=0.9,
        dead_beats=3,
        clock=lambda: net.now,
        metrics=Metrics(),
    )
    bal.register(0, make_server(), addr=("mig", 0),
                 sock=net.socket(("mig", 0)))
    hb = net.socket(("hb", 0))
    for seq, gap in ((1, 0.62), (2, 0.62)):
        hb.send_to(
            proto.encode(
                proto.FleetHeartbeat(0, 10, 1, 3, 0, 0, beat_seq=seq)
            ),
            ("fleet", "bal"),
        )
        net.advance(0.0)
        bal.pump()
        assert bal.members[0].missed_beats == 0
        net.advance(gap)
        assert bal.check() == []  # 2 missed < dead_beats, every cycle
    assert bal.members[0].alive
    row = next(r for r in bal.fleet_rows() if r["server_id"] == 0)
    assert row["missed_beats"] == 2


# ---------------------------------------------------------------------------
# Partition-aware autopilot degradation
# ---------------------------------------------------------------------------


DEG_CFG = AutopilotConfig(
    low_watermark=0.5,
    confirm_beats=2,
    min_servers=2,
    max_servers=4,
    cooldown_scale_ticks=0,
    suspect_beats=2,
)


def _obs(tick, missed, reachable=True):
    servers = {
        0: ServerSample(0, 0, 4, missed_beats=missed, reachable=reachable),
        1: ServerSample(1, 1, 3),
        2: ServerSample(2, 1, 3),
    }
    return FleetObservation(
        tick=tick, servers=servers, placements={10: 1, 11: 2}, backups={}
    )


def test_policy_enters_degraded_and_freezes_scale_down():
    pol = AutopilotPolicy(DEG_CFG)
    a0 = pol.decide(_obs(0, 0))
    a1 = pol.decide(_obs(1, 2))  # server 0 suspect: 2 missed, reachable
    a2 = pol.decide(_obs(2, 3))  # still suspect: no repeat emissions
    kinds1 = [a.kind for a in a1]
    assert "partition_suspected" in kinds1 and "degraded_enter" in kinds1
    assert not any(
        a.kind in ("partition_suspected", "degraded_enter") for a in a2
    )
    # Occupancy sat below the low watermark the whole time, but
    # scale-down is frozen while degraded.
    assert not any(a.kind == "scale_down" for a in a0 + a1 + a2)
    a3 = pol.decide(_obs(3, 0))  # beats return
    assert any(a.kind == "degraded_exit" for a in a3)
    a4 = pol.decide(_obs(4, 0))
    a5 = pol.decide(_obs(5, 0))
    assert any(a.kind == "scale_down" for a in a4 + a5)  # thawed
    assert pol.degraded_beats == 2


def test_unreachable_server_is_not_suspect():
    """Missed beats with a FAILED probe is the dead-server signature —
    the failover reflex's business, not a degraded-mode episode."""
    pol = AutopilotPolicy(DEG_CFG)
    acts = pol.decide(_obs(0, 5, reachable=False))
    assert not any(a.kind == "partition_suspected" for a in acts)
    assert not pol._degraded


def test_suspect_server_is_not_a_migration_destination():
    cfg = AutopilotConfig(
        preempt_pages=1, preempt_confirm=1, suspect_beats=2,
        cooldown_preempt_ticks=0,
    )
    pol = AutopilotPolicy(cfg)
    servers = {
        0: ServerSample(0, 2, 2, pages=3),       # burning source
        1: ServerSample(1, 0, 4, missed_beats=2),  # suspect: excluded
        2: ServerSample(2, 1, 3),
    }
    obs = FleetObservation(
        tick=0, servers=servers, placements={10: 0}, backups={}
    )
    acts = pol.decide(obs)
    moves = [a for a in acts if a.kind == "preempt_migrate"]
    assert moves and all(a.dst_id == 2 for a in moves)


def test_degraded_ledger_replays_identically():
    """The degraded-mode fields round-trip through the ledger and a
    fresh policy re-derives the exact same typed actions — including
    partition_suspected / degraded_enter / degraded_exit."""
    obs_seq = [
        _obs(0, 0), _obs(1, 2), _obs(2, 3),
        _obs(3, 0), _obs(4, 0), _obs(5, 0),
    ]
    rec_pol = AutopilotPolicy(DEG_CFG)
    records = [
        {
            "observation": observation_to_json(o),
            "actions": [_action_to_json(a) for a in rec_pol.decide(o)],
        }
        for o in obs_seq
    ]
    assert any(
        a["kind"] == "degraded_enter" for r in records for a in r["actions"]
    )
    replayed = replay_ledger(records, DEG_CFG)
    assert [
        [_action_to_json(a) for a in acts] for acts in replayed
    ] == [r["actions"] for r in records]


def test_observation_json_backward_compatible():
    raw = observation_to_json(_obs(1, 2))
    back = observation_from_json(raw)
    assert back.servers[0].missed_beats == 2
    assert back.servers[0].reachable is True
    # A pre-degraded-mode ledger (no new fields) still loads: defaults.
    legacy = {
        **raw,
        "servers": {
            sid: {
                k: v
                for k, v in s.items()
                if k not in ("missed_beats", "reachable")
            }
            for sid, s in raw["servers"].items()
        },
    }
    old = observation_from_json(legacy)
    assert old.servers[0].missed_beats == 0 and old.servers[0].reachable


# ---------------------------------------------------------------------------
# ChaosPlan: the control-plane family rides last
# ---------------------------------------------------------------------------


def test_control_family_appends_after_elastic_draws():
    kw = dict(
        seed=5, duration=20.0, peers=("a", "b"),
        fleet=(0, 1, 2), fleet_matches=4, elastic=True,
    )
    base = ChaosPlan.generate(**kw)
    plan = ChaosPlan.generate(control=True, **kw)
    # Pinned: every pre-control draw is byte-identical.
    assert plan.directives[: len(base.directives)] == base.directives
    extra = plan.directives[len(base.directives):]
    assert [type(d).__name__ for d in extra] == [
        "Corrupt", "Duplicate", "Partition"
    ]
    part = extra[2]
    assert part.src in (0, 1, 2) and part.dst is None  # asymmetric, by id
    assert ChaosPlan.from_json(plan.to_json()).directives == plan.directives


# ---------------------------------------------------------------------------
# The chaotic elastic soak: full arc under control-plane chaos
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_chaotic_elastic_autopilot_soak(tmp_path):
    """The tentpole, end to end against real processes: the N=3
    elasticity arc (scale-up -> burn preemption -> drain-pack ->
    retire) with every child UDP socket behind a ChaosSocket running
    continuous loss/duplication/corruption/reorder plus an asymmetric
    partition on server 0's outbound. Same bar as the calm soak: zero
    matches lost, zero false failovers, zero duplicate-match landings,
    zero steady-state recompiles, ledger replays identical — plus proof
    the chaos actually bit (injected faults > 0, retransmits > 0)."""
    from bevy_ggrs_tpu.fleet.autopilot import FleetAutopilot, verify_ledger
    from bevy_ggrs_tpu.fleet.proc import ProcFleet
    from tests.test_fleet_proc import BASE, match_frames, pump_until

    plan = ChaosPlan(
        seed=11,
        directives=(
            # Continuous low-grade noise on every child datagram — the
            # reliable sublayer's steady diet.
            LossBurst(0.0, 1e9, 0.15),
            Duplicate(0.0, 1e9, 0.10),
            Corrupt(0.0, 1e9, 0.05),
            Reorder(0.0, 1e9, 0.10, delay=0.05),
            # One asymmetric partition: server 0's sends go dark while it
            # still hears the world. Short of the death threshold — the
            # suspect path must hold the fleet together, not failover.
            Partition(12.0, 18.0, src=0),
        ),
    )
    fleet = ProcFleet(
        str(tmp_path / "fleet"),
        base_config=BASE,
        heartbeat_timeout=8.0,
        chaos_plan=plan,
    )
    cfg = AutopilotConfig(
        high_watermark=0.8,
        low_watermark=0.3,
        confirm_beats=3,
        preempt_confirm=2,
        preempt_batch=1,
        cooldown_scale_ticks=40,
        cooldown_preempt_ticks=20,
        min_servers=2,
        max_servers=4,
        suspect_beats=2,
    )
    ap = FleetAutopilot(fleet, config=cfg)
    tickbox = {"t": 0}

    def tick():
        ap.step(tickbox["t"])
        tickbox["t"] += 1
        for dead in fleet.check():
            fleet.failover(dead, preferred=ap.backups)

    try:
        for _ in range(2):
            fleet.spawn_server(wait_ready=True)

        # Phase 1 — fill to the high watermark; the policy scales to 3.
        for mid in range(7):
            fleet.admit(mid)

        def all_admitted():
            missing = [m for m in range(7) if m not in fleet.handles]
            for mid in missing:
                if mid not in fleet.book:
                    fleet.admit(mid)
            return not missing

        pump_until(fleet, all_admitted, timeout=120, tick=tick,
                   msg="arrivals admitted under chaos")
        pump_until(fleet, lambda: len(fleet.samples()) == 3, timeout=180,
                   tick=tick, msg="scale-up to N=3 under chaos")
        new_sid = max(fleet.members)
        for mid in (100, 101):
            fleet.admit(mid, new_sid)
        pump_until(
            fleet,
            lambda: match_frames(fleet, new_sid).get(100, 0) > 20,
            timeout=120, tick=tick, msg="new server serving",
        )
        for m in fleet.members.values():
            m.process.send(cmd="rebase_compiles")

        # Phase 2 — burn window: preemption must land under chaos.
        donor = 0
        fleet.members[donor].process.send(
            cmd="hiccup", every=3, ms=60.0, frames=400
        )
        pump_until(
            fleet,
            lambda: any(
                e["event"] == "migrated" and e["src"] == donor
                for e in fleet.events
            ),
            timeout=180, tick=tick,
            msg="burn-triggered preemption completing under chaos",
        )
        assert fleet.matches_lost == 0
        pump_until(
            fleet, lambda: fleet.members[donor].info.pages == 0,
            timeout=180, tick=tick, msg="pages clearing",
        )

        # Phase 3 — traffic drop: drain-pack-retire must finish.
        keep = {}
        for mid, sid in sorted(fleet.placements().items()):
            keep.setdefault(sid, mid)
        # Fill-ins race the autopilot's own drain-pack decisions: a
        # draining child refuses admits (typed admit_failed, un-booked
        # by the parent), so skip drainers and let a refusal release
        # the wait instead of deadlocking it.
        for sid, sample in sorted(fleet.samples().items()):
            if sid not in keep and not sample.draining:
                fleet.admit(200 + sid, sid)
                keep[sid] = 200 + sid
        pump_until(
            fleet,
            lambda: all(
                m in fleet.handles or m not in fleet.book
                for m in keep.values()
            ),
            timeout=120, tick=tick, msg="fill-in admissions serving",
        )
        for mid in sorted(fleet.placements()):
            if mid not in keep.values():
                assert fleet.retire_match(mid)
        pump_until(
            fleet,
            lambda: any(e["event"] == "retired" for e in fleet.events),
            timeout=240, tick=tick,
            msg="drain-pack-retire completing under chaos",
        )
        # Packing to min_servers can take several retire cycles (each
        # gated by the scale cooldown) when chaos-era pages grew the
        # fleet past N=3 — wait for the whole pack-down, then for every
        # retired child to actually exit.
        pump_until(
            fleet, lambda: len(fleet.samples()) == 2,
            timeout=300, tick=tick,
            msg="packing down to min_servers under chaos",
        )
        for victim in sorted(
            {e["server"] for e in fleet.events if e["event"] == "retired"}
        ):
            pump_until(
                fleet,
                lambda v=victim: not fleet.members[v].process.alive(),
                timeout=120, tick=tick,
                msg=f"retired child {victim} exiting",
            )
        assert len(fleet.samples()) == 2

        # The hard bar, identical to the calm soak's:
        assert fleet.matches_lost == 0
        assert fleet.failovers == 0  # the partition never faked a death
        # No duplicate-match landings anywhere: fresh status from every
        # survivor, then every hosted match appears on exactly one.
        # Capture over the live SERVING set, not everything with a pid:
        # a just-retired child can still be mid-exit here, and its frame
        # counter will never advance again.
        frames_before = {
            sid: (fleet.members[sid].status or {}).get("frames", 0)
            for sid in fleet.samples()
        }
        deadline = time.time() + 120.0
        while True:
            fleet.pump()
            tick()
            serving = [s for s in frames_before if s in fleet.samples()]
            fresh = {
                sid: (fleet.members[sid].status or {}).get("frames", 0)
                for sid in frames_before
            }
            if serving and all(
                fresh[s] > frames_before[s] for s in serving
            ):
                break
            if time.time() > deadline:
                alive = {
                    sid: fleet.members[sid].process.alive()
                    for sid in frames_before
                }
                pytest.fail(
                    "fresh post-arc status: "
                    f"before={frames_before} now={fresh} alive={alive} "
                    f"placements={fleet.placements()} "
                    f"tail={fleet.events[-8:]}"
                )
            time.sleep(0.03)
        hosted = {}
        for sid, m in fleet.members.items():
            if m.process.alive() and m.status:
                for mid in m.status.get("matches", {}):
                    hosted.setdefault(int(mid), set()).add(sid)
        assert all(len(s) == 1 for s in hosted.values()), hosted
        # Zero churn recompiles since steady state, despite the chaos.
        for sid, m in fleet.members.items():
            if m.process.alive() and m.status is not None:
                assert m.status["compiles"] == 0
                assert m.status["faults"] == 0

        # Chaos actually bit, and the reliable wire absorbed it.
        assert fleet.chaos_faults > 0
        assert fleet.ctrl_retransmits > 0

        # The decision ledger — degraded entries included — replays
        # bit-identically offline.
        ledger_path = os.path.join(str(tmp_path), "chaos_ledger.jsonl")
        ap.export_jsonl(ledger_path)
        ok, ticks = verify_ledger(ledger_path)
        assert ok and ticks == len(ap.ledger)
    finally:
        fleet.close()
