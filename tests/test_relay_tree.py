"""Relay tree: tiered spectator fan-out (relay/tree.py).

Covers the whole tree surface: depth-2 bitwise exactness at every leaf
against the authoritative ring (the tier link feeds raw datagrams, so
exactness is structural), the shared-keyframe cache (N cold joins in one
interval cost ONE upstream encode; stream-epoch invalidation; corrupt
cached entries refused by digest and rebuilt), chain-aware warm resume
across a relay swap (zero keyframe bytes on the wire — the satellite
fix), KEYFRAME_ONLY parent propagation (children re-seed, no silent
chain break), the mid-tier kill soak (re-home ladder, zero desync,
bounded resume), relay-tier autopilot elasticity (spawn -> fan-out ->
drain -> retire, ledger replays bit-identically), RelayTreeKill plan
stability (drawn LAST; old seeds stay byte-identical), and a subprocess
relay tier over real UDP.
"""

import json
import os
import zlib

import numpy as np
import pytest

from bevy_ggrs_tpu.chaos import (
    ChaosPlan,
    ChaosSocket,
    LossBurst,
    Partition,
    RelayTreeKill,
    Reorder,
)
from bevy_ggrs_tpu.fleet.autopilot import (
    RelayAutopilot,
    RelayAutopilotConfig,
    RelayObservation,
    RelayPolicy,
    RelaySample,
    verify_relay_ledger,
)
from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.relay import (
    RelayServer,
    StateCodec,
    StatePublisher,
    StreamSpectator,
    payload_digest,
)
from bevy_ggrs_tpu.relay.server import MODE_FULL, MODE_KEYFRAME
from bevy_ggrs_tpu.relay.stream import CHUNK_PAYLOAD
from bevy_ggrs_tpu.relay.tree import ProcRelayTier, RelayTree
from bevy_ggrs_tpu.runner import RollbackRunner
from bevy_ggrs_tpu.session import EventKind, SessionState
from bevy_ggrs_tpu.session import protocol as proto
from bevy_ggrs_tpu.session.common import NULL_FRAME
from bevy_ggrs_tpu.session.requests import AdvanceFrame
from bevy_ggrs_tpu.transport.loopback import LoopbackNetwork
from bevy_ggrs_tpu.utils.metrics import Metrics
from tests.test_p2p import FPS_DT, scripted_input
from tests.test_relay import FakeSocket, make_relay_peer
from tests.test_supervisor import MAX_PRED, settled_checksums, sup_step

SESSION = 7
ROOT = ("relay", 0)


def _kf_raws(frame, data):
    """Hand-craft a chunked StreamKeyframe exactly as StatePublisher
    would ship it (same chunking, crc, digest)."""
    digest = payload_digest(data)
    chunks = [
        data[i : i + CHUNK_PAYLOAD]
        for i in range(0, len(data), CHUNK_PAYLOAD)
    ] or [b""]
    return [
        proto.encode(
            proto.StreamKeyframe(
                frame, seq, len(chunks),
                zlib.crc32(p) & 0xFFFFFFFF, digest, p,
            )
        )
        for seq, p in enumerate(chunks)
    ]


def _tree_fixture(
    net,
    mids=2,
    leaf_under=None,
    server_kwargs=None,
    socket_factory=None,
    max_depth=2,
):
    """Root + ``mids`` tier-1 relays (+ optionally one tier-2 leaf
    under ``leaf_under``) with per-relay Metrics, 2 relay-peers through
    the root, and a publisher on peer 0."""
    tree = RelayTree(
        socket_factory if socket_factory is not None else net.socket,
        session_id=SESSION,
        clock=lambda: net.now,
        max_depth=max_depth,
        metrics_factory=lambda addr: Metrics(),
        server_kwargs=server_kwargs or {},
    )
    tree.add_relay(addr=ROOT)
    mid_nodes = [tree.add_relay(parent=ROOT) for _ in range(mids)]
    leaf_node = (
        tree.add_relay(parent=leaf_under) if leaf_under is not None else None
    )
    a = make_relay_peer(net, 2, 0, [ROOT])
    b = make_relay_peer(net, 2, 1, [ROOT])
    pub = StatePublisher(
        a[0], a[1], socket=a[0].socket, keyframe_interval=10,
        max_frames_per_publish=1,
    )
    return tree, mid_nodes, leaf_node, (a, b), pub


def _make_spec(net, addr, relays, codec, **kw):
    kw.setdefault("session_id", SESSION)
    kw.setdefault("window", 8)
    kw.setdefault("clock", lambda: net.now)
    kw.setdefault("resub_timeout", 0.6)
    return StreamSpectator(net.socket(addr), relays=relays, codec=codec, **kw)


# ---------------------------------------------------------------------------
# Depth-2 bitwise exactness at every leaf
# ---------------------------------------------------------------------------


class TestRelayTreeExactness:
    def test_depth2_streams_bitwise_exact_at_every_leaf(self):
        """Acceptance: root -> mid -> leaf relays, spectators at every
        tier. Every frame every spectator reconstructs equals the
        authoritative ring state bitwise, and the final frame matches an
        independent serial replay of the scripted inputs."""
        net = LoopbackNetwork()
        tree, mid_nodes, leaf_node, peers, pub = _tree_fixture(
            net, mids=2, leaf_under=None,
        )
        mid0, mid1 = mid_nodes
        leaf_node = tree.add_relay(parent=mid0.addr)
        assert leaf_node.tier == 2 and tree.depth() == 2

        codec = StateCodec.for_state(box_game.make_world(2).commit())
        specs = [
            _make_spec(net, ("spec", i), [addr], codec, max_apply_per_poll=1)
            for i, addr in enumerate(
                [mid0.addr, mid1.addr, leaf_node.addr]
            )
        ]
        authoritative = {}
        checked = [0, 0, 0]

        def drain(spec, i):
            while True:
                prev = spec.current_frame
                spec.poll(net.now)
                if spec.current_frame == prev:
                    return
                f = spec.current_frame
                if f in authoritative:
                    assert spec.state_bytes == authoritative[f], (
                        f"spec {i} diverged at frame {f}"
                    )
                    checked[i] += 1

        for _ in range(300):
            net.advance(FPS_DT)
            tree.pump(net.now)
            for peer in peers:
                sup_step(net, peer, scripted_input)
            before = pub.published_frames
            pub.publish(net.now)
            if pub.published_frames > before:
                authoritative[pub._prev_frame] = pub._prev
            for i, spec in enumerate(specs):
                drain(spec, i)

        # Drain: peers stop advancing; the stream flushes down the tree.
        for _ in range(40):
            net.advance(FPS_DT)
            tree.pump(net.now)
            for session, _, _, _ in peers:
                session.poll_remote_clients()
            pub.publish(net.now)
            for i, spec in enumerate(specs):
                drain(spec, i)

        assert len(authoritative) >= 150
        for i, spec in enumerate(specs):
            assert spec.current_frame == pub._prev_frame, f"spec {i} lagged"
            assert spec.state_bytes == pub._prev
            assert checked[i] >= 150
            assert spec.deltas_applied >= 100  # rode the chain, not keyframes
        # The tree is caught up: no tier holds residual lag after drain.
        assert all(lag == 0 for lag in tree.tier_lag().values())

        # Independent serial replay anchor: exact w.r.t. the true
        # trajectory, not just the publisher's own ring.
        F = specs[2].current_frame
        ref = RollbackRunner(
            box_game.make_schedule(),
            box_game.make_world(2).commit(),
            max_prediction=MAX_PRED,
            num_players=2,
            input_spec=box_game.INPUT_SPEC,
        )
        for f in range(F):
            bits = np.stack([scripted_input(h, f) for h in range(2)])
            ref.handle_requests(
                [AdvanceFrame(bits=bits, status=np.zeros(2, np.int32))]
            )
        assert codec.encode(ref.world()) == specs[2].state_bytes

    def test_topology_rows_and_report_section(self):
        """topology_rows feeds the ops report's tree section."""
        net = LoopbackNetwork()
        tree, mid_nodes, _, _, _ = _tree_fixture(net, mids=2)
        tree.add_relay(parent=mid_nodes[0].addr)
        rows = tree.topology_rows()
        assert len(rows) == 4
        assert [r["tier"] for r in rows] == [0, 1, 1, 2]
        assert rows[0]["parent"] == "" and rows[3]["alive"]
        from bevy_ggrs_tpu.obs.report import build_report

        html = build_report(relay_tree=rows, title="tree test")
        assert "Relay tree" in html and "tier 2" in html
        # Empty trees render a placeholder, not a broken table.
        assert "no relay-tree members" in build_report(relay_tree=[])


# ---------------------------------------------------------------------------
# Shared-keyframe cache
# ---------------------------------------------------------------------------


def _relay_with_stream(data=b"\x55" * 2600, frame=40, **kw):
    """RelayServer + an ingested chunked keyframe (no match needed)."""
    sock = FakeSocket(addr=("relay", 9))
    relay = RelayServer(sock, clock=lambda: 0.0, metrics=Metrics(), **kw)
    for raw in _kf_raws(frame, data):
        assert relay.ingest(SESSION, raw)
    return relay, sock, payload_digest(data)


def _cold_join(relay, addr, now=0.0):
    relay.socket.inbox.append(
        (addr, proto.encode(proto.Subscribe(SESSION, NULL_FRAME, 8)))
    )
    relay.pump(now)


class TestSharedKeyframeCache:
    def test_n_cold_joins_one_upstream_encode(self):
        """Satellite acceptance: N cold joins inside one keyframe
        interval cost exactly ONE upstream encode (the periodic publish
        that produced the keyframe) — the relay re-serves it from the
        content-addressed cache, never asking upstream again."""
        net = LoopbackNetwork()
        relay = RelayServer(
            net.socket(ROOT), clock=lambda: net.now, metrics=Metrics(),
        )
        a = make_relay_peer(net, 2, 0, [ROOT])
        b = make_relay_peer(net, 2, 1, [ROOT])
        pub = StatePublisher(
            a[0], a[1], socket=a[0].socket, keyframe_interval=10,
        )
        encodes = [0]
        for _ in range(140):
            net.advance(FPS_DT)
            relay.pump(net.now)
            for peer in (a, b):
                sup_step(net, peer, scripted_input)
            pub.publish(net.now)
            if pub.codec is not None and not hasattr(pub.codec, "_counted"):
                orig = pub.codec.encode

                def counting_encode(state, _orig=orig):
                    encodes[0] += 1
                    return _orig(state)

                pub.codec.encode = counting_encode
                pub.codec._counted = True
        assert pub.published_frames > 60
        assert relay.stream_latest_keyframe(SESSION) is not None

        # Freeze the match: from here, any upstream encode would be
        # join-driven — the witness the cache must keep at zero.
        codec = StateCodec.for_state(box_game.make_world(2).commit())
        n = 6
        specs = [
            _make_spec(net, ("cold", i), [ROOT], codec) for i in range(n)
        ]
        encodes_before = encodes[0]
        for _ in range(30):
            net.advance(FPS_DT)
            relay.pump(net.now)
            for session, _, _, _ in (a, b):
                session.poll_remote_clients()
            for spec in specs:
                spec.poll(net.now)

        assert encodes[0] == encodes_before  # ONE-encode witness
        for spec in specs:
            assert spec.state_bytes is not None
            assert spec.current_frame >= relay.stream_latest_keyframe(SESSION)
        c = relay.metrics.counters
        assert c["keyframe_cache_misses"] == 1  # first serve populates
        assert c["keyframe_cache_hits"] >= n - 1  # the rest are cache hits
        assert relay.keyframe_cache.hits >= n - 1

    def test_cache_invalidated_on_stream_epoch_change(self):
        relay, sock, digest = _relay_with_stream()
        _cold_join(relay, ("s", 0))
        assert len(relay.keyframe_cache) == 1 and digest in relay.keyframe_cache
        relay.reset_stream(SESSION)
        assert len(relay.keyframe_cache) == 0
        assert relay.metrics.counters["fanout_stream_resets"] == 1
        # A fresh stream instance repopulates cleanly.
        new = b"\xaa" * 2600
        for raw in _kf_raws(50, new):
            relay.ingest(SESSION, raw)
        _cold_join(relay, ("s", 1))
        assert payload_digest(new) in relay.keyframe_cache
        assert digest not in relay.keyframe_cache

    def test_corrupt_cached_entry_refused_by_digest_and_refetched(self):
        relay, sock, digest = _relay_with_stream()
        _cold_join(relay, ("s", 0))  # miss + populate
        assert relay.metrics.counters["keyframe_cache_misses"] == 1
        # Flip a byte inside the cached raw datagram: the next lookup
        # must refuse it (per-chunk crc / digest), purge, and rebuild
        # from the intact stream buffer.
        entry = relay.keyframe_cache._entries[digest]
        raw0 = bytearray(entry["chunks"][0])
        raw0[-1] ^= 0xFF
        entry["chunks"][0] = bytes(raw0)
        sent_before = len(sock.sent)
        _cold_join(relay, ("s", 1))
        assert relay.keyframe_cache.corrupt == 1
        assert relay.metrics.counters["keyframe_cache_corrupt"] == 1
        # The join was still served — with the CORRECT bytes.
        served = [
            proto.decode(d) for d, addr in sock.sent[sent_before:]
            if addr == ("s", 1)
        ]
        kfs = [m for m in served if isinstance(m, proto.StreamKeyframe)]
        assert kfs and payload_digest(
            b"".join(m.payload for m in sorted(kfs, key=lambda m: m.seq))
        ) == digest
        # And the cache healed: the rebuilt entry validates again.
        assert relay.keyframe_cache.lookup(digest) is not None
        assert relay.keyframe_cache.corrupt == 1  # no new corruption

    def test_cache_capacity_fifo_eviction(self):
        from bevy_ggrs_tpu.relay.server import KeyframeCache

        cache = KeyframeCache(capacity=2)
        for i, data in enumerate([b"a" * 40, b"b" * 40, b"c" * 40]):
            cache.put(payload_digest(data), i, _kf_raws(i, data))
        assert len(cache) == 2
        assert payload_digest(b"a" * 40) not in cache
        assert cache.lookup(payload_digest(b"c" * 40)) is not None


# ---------------------------------------------------------------------------
# Chain-aware warm resume (the relay-swap keyframe fix)
# ---------------------------------------------------------------------------


class TestWarmFailoverResume:
    def test_warm_swap_costs_zero_keyframe_bytes(self):
        """Satellite fix pin: a spectator bounces mid0 -> mid1 -> mid0.
        While it is away, mid0's stale entry degrades to KEYFRAME_ONLY;
        on return its delta chain is still contiguous, so the resume
        must promote straight back to FULL — bytes-on-wire shows ZERO
        keyframe bytes after the swap settles."""
        net = LoopbackNetwork()
        tree, (mid0, mid1), _, peers, pub = _tree_fixture(net, mids=2)
        codec = StateCodec.for_state(box_game.make_world(2).commit())
        spec_metrics = Metrics()
        spec = _make_spec(
            net, ("spec", 0), [mid0.addr], codec, metrics=spec_metrics,
        )

        def run(ticks):
            for _ in range(ticks):
                net.advance(FPS_DT)
                tree.pump(net.now)
                for peer in peers:
                    sup_step(net, peer, scripted_input)
                pub.publish(net.now)
                spec.poll(net.now)

        run(140)  # warm up on mid0
        assert spec.state_bytes is not None
        assert mid0.server.subscriber_mode(("spec", 0)) == MODE_FULL

        spec.retarget([mid1.addr])  # swap away; mid0 entry goes stale
        run(35)
        assert spec.frames_behind() <= 8  # warm on mid1 too
        # The stale mid0 entry degraded while the spectator was away —
        # exactly the rung the chain-aware resume must clear.
        assert mid0.server.subscriber_mode(("spec", 0)) == MODE_KEYFRAME

        spec.retarget([mid0.addr])  # swap back
        # One tick flushes the in-flight keyframe spam the stale entry
        # sent BEFORE the re-subscribe landed; everything after this
        # snapshot is post-resume traffic — the bytes being pinned.
        run(1)
        kf_bytes = spec_metrics.counters["stream_keyframe_bytes_received"]
        delta_bytes = spec_metrics.counters["stream_delta_bytes_received"]
        run(45)
        assert spec_metrics.counters["stream_keyframe_bytes_received"] == \
            kf_bytes, "warm swap-back re-requested a keyframe"
        assert spec_metrics.counters["stream_delta_bytes_received"] > \
            delta_bytes  # the chain kept flowing
        assert mid0.server.metrics.counters["fanout_resumed_warm"] >= 1
        assert mid0.server.subscriber_mode(("spec", 0)) == MODE_FULL

        # And the resumed stream is still bitwise exact.
        for _ in range(30):
            net.advance(FPS_DT)
            tree.pump(net.now)
            for session, _, _, _ in peers:
                session.poll_remote_clients()
            pub.publish(net.now)
            spec.poll(net.now)
        assert spec.current_frame == pub._prev_frame
        assert spec.state_bytes == pub._prev


# ---------------------------------------------------------------------------
# KEYFRAME_ONLY parent propagation
# ---------------------------------------------------------------------------


class TestKeyframeOnlyParentPropagation:
    def test_degraded_parent_does_not_break_child_chains(self):
        """An ack partition on the uplink degrades the ROOT's view of
        the tier link to KEYFRAME_ONLY. The child keeps ingesting the
        keyframes, its own subscribers re-seed from them (epoch-style),
        and after the heal both ladders recover to FULL — bitwise
        throughout."""
        net = LoopbackNetwork()
        uplink_addr = ((("relay", 1)), "uplink")
        plan = ChaosPlan(31, (Partition(1.5, 2.5, src=uplink_addr),))

        def factory(addr):
            sock = net.socket(addr)
            if addr == uplink_addr:
                return ChaosSocket(
                    sock, plan, clock=lambda: net.now, addr=addr
                )
            return sock

        tree, (mid0,), _, peers, pub = _tree_fixture(
            net, mids=1, socket_factory=factory,
            server_kwargs=dict(degrade_after=8, shed_after=5.0),
        )
        codec = StateCodec.for_state(box_game.make_world(2).commit())
        spec = _make_spec(net, ("spec", 0), [mid0.addr], codec)
        root_srv = tree.node(ROOT).server

        link_modes, kf_in_window = set(), [0]
        for _ in range(260):
            net.advance(FPS_DT)
            tree.pump(net.now)
            for peer in peers:
                sup_step(net, peer, scripted_input)
            pub.publish(net.now)
            before = spec.keyframes_applied
            spec.poll(net.now)
            if 1.5 < net.now < 2.5:
                m = root_srv.subscriber_mode(uplink_addr)
                if m is not None:
                    link_modes.add(m)
                kf_in_window[0] += spec.keyframes_applied - before

        # The root degraded the LINK, not just a spectator...
        assert MODE_KEYFRAME in link_modes
        assert root_srv.metrics.counters["fanout_degraded"] >= 1
        # ...and the child's subscriber survived ON keyframes that the
        # link kept ingesting (no silent chain break).
        assert kf_in_window[0] >= 1
        assert mid0.server.metrics.counters["fanout_degraded"] >= 1

        # Post-heal: both tiers recovered and the leaf converges.
        for _ in range(40):
            net.advance(FPS_DT)
            tree.pump(net.now)
            for session, _, _, _ in peers:
                session.poll_remote_clients()
            pub.publish(net.now)
            spec.poll(net.now)
        assert root_srv.subscriber_mode(uplink_addr) == MODE_FULL
        assert root_srv.metrics.counters["fanout_recovered"] >= 1
        assert spec.current_frame == pub._prev_frame
        assert spec.state_bytes == pub._prev


# ---------------------------------------------------------------------------
# Mid-tier kill soak: re-home ladder under loss/reorder
# ---------------------------------------------------------------------------


class TestRelayTreeKillSoak:
    def test_midtier_kill_rehomes_zero_desync_bounded_resume(self):
        """Acceptance soak: a scripted RelayTreeKill takes out mid0
        (which owns a tier-2 child relay and direct spectators) under
        spectator loss + reorder. The orphaned child re-homes to the
        sibling (ladder rung 1), spectators re-home client-side with
        their cursors, a replacement relay spawns after the window —
        zero desync, every spectator resumes within 8 frames, bitwise
        exact at the end."""
        net = LoopbackNetwork()
        tree, (mid0, mid1), leaf, peers, pub = _tree_fixture(
            net, mids=2, server_kwargs=dict(shed_after=5.0),
        )
        leaf = tree.add_relay(parent=mid0.addr)
        plan = ChaosPlan(91, (
            Reorder(1.0, 2.2, 0.2, delay=0.03),
            RelayTreeKill(3.0, mid0.addr, 0.5),
        ))
        spec_plan = ChaosPlan(92, (LossBurst(1.2, 2.4, 0.25),))
        kill = plan.relay_tree_kills()[0]
        assert kill.relay == mid0.addr

        codec = StateCodec.for_state(box_game.make_world(2).commit())
        specs = []
        for i, target in enumerate([mid0.addr, leaf.addr, mid1.addr]):
            inner = net.socket(("spec", i))
            sock = ChaosSocket(
                inner, spec_plan, clock=lambda: net.now, addr=("spec", i)
            )
            specs.append(StreamSpectator(
                sock, relays=[target], session_id=SESSION, window=8,
                codec=codec, clock=lambda: net.now, resub_timeout=0.6,
                metrics=Metrics(),
            ))

        killed = respawned = False
        rehomed = []
        events = []
        for _ in range(int(6.5 / FPS_DT)):
            net.advance(FPS_DT)
            if not killed and net.now >= kill.at:
                rehomed = tree.kill(mid0.addr)
                killed = True
                # Client-side re-home: the dead relay's spectators move
                # to where their subtree went (the ladder target).
                specs[0].retarget([mid1.addr], now=net.now)
            if killed and not respawned and net.now >= kill.at + kill.down_for:
                assert tree.spawn_relay()  # elastic replacement
                respawned = True
            tree.pump(net.now)
            for peer in peers:
                sup_step(net, peer, scripted_input, events)
            pub.publish(net.now)
            for spec in specs:
                spec.poll(net.now)

        # Drain to the stream head.
        for _ in range(30):
            net.advance(FPS_DT)
            tree.pump(net.now)
            for session, _, _, _ in peers:
                session.poll_remote_clients()
            pub.publish(net.now)
            for spec in specs:
                spec.poll(net.now)

        # CI forensics land BEFORE the assertions (ops report includes
        # the tree topology section).
        obs_dir = os.environ.get("GGRS_OBS_DIR")
        if obs_dir:
            os.makedirs(obs_dir, exist_ok=True)
            from bevy_ggrs_tpu.obs.report import build_report

            build_report(
                os.path.join(obs_dir, "relay_tree_soak.html"),
                title="relay tree kill soak",
                relay_tree=tree.topology_rows(),
                notes=f"plan seed 91; kill at {kill.at}s",
            )
            with open(os.path.join(obs_dir, "relay_tree_soak.json"), "w") as f:
                json.dump({
                    "plan": json.loads(plan.to_json()),
                    "tree_events": [
                        {k: repr(v) for k, v in e.items()}
                        for e in tree.events
                    ],
                    "spectators": [
                        {"frame": s.current_frame,
                         "behind": s.frames_behind(),
                         "keyframe_bytes": s.metrics.counters[
                             "stream_keyframe_bytes_received"],
                         } for s in specs
                    ],
                }, f, indent=2)

        # --- topology: the ladder re-homed the orphaned subtree -------
        assert killed and respawned
        assert rehomed == [leaf.addr]
        assert leaf.parent == mid1.addr and leaf.tier == 2
        assert leaf.link.retargets == 1
        kinds = [e["event"] for e in tree.events]
        assert "kill" in kinds and "rehome" in kinds and kinds.count("spawn") == 5

        # --- match plane: untouched by the fan-out tier death ---------
        assert not any(e.kind == EventKind.DESYNC_DETECTED for e in events)
        assert not any(e.kind == EventKind.DISCONNECTED for e in events)
        for session, _, _, _ in peers:
            assert session.current_state() == SessionState.RUNNING
        frames, rows = settled_checksums([p[0] for p in peers])
        assert len(frames) >= 3
        for f, row in zip(frames, rows):
            assert len(set(row)) == 1, f"frame {f} desynced"

        # --- spectators: bounded resume, bitwise exact ----------------
        RESUME_BOUND = 8  # frames — THE acceptance bound
        for i, spec in enumerate(specs):
            assert spec.state_bytes is not None
            assert spec.frames_behind() <= RESUME_BOUND, (
                f"spec {i} is {spec.frames_behind()} frames behind"
            )
            assert spec.current_frame == pub._prev_frame
            assert spec.state_bytes == pub._prev, f"spec {i} diverged"


# ---------------------------------------------------------------------------
# Relay-tier autopilot elasticity
# ---------------------------------------------------------------------------


def _sample(rid, tier=1, parent=0, subs=0, cap=4, alive=True, draining=False):
    return RelaySample(
        relay_id=rid, tier=tier, parent_id=parent, subscribers=subs,
        capacity=cap, alive=alive, draining=draining,
    )


class TestRelayPolicy:
    def test_scale_up_needs_confirm_streak(self):
        pol = RelayPolicy(RelayAutopilotConfig(confirm_beats=3))
        obs = lambda t: RelayObservation(t, {1: _sample(1, subs=4)})
        assert pol.decide(obs(0)) == []
        assert pol.decide(obs(1)) == []
        acts = pol.decide(obs(2))
        assert [a.kind for a in acts] == ["relay_spawn"]

    def test_orphan_rehomes_to_closest_live_tier_once(self):
        pol = RelayPolicy()
        relays = {
            1: _sample(1, tier=1, parent=0, subs=1),
            2: _sample(2, tier=2, parent=9, subs=1, alive=False),  # orphan
        }
        acts = pol.decide(RelayObservation(0, relays))
        assert [a.kind for a in acts] == ["relay_rehome"]
        assert acts[0].server_id == 2 and acts[0].dst_id == 1
        # One action per orphan per episode.
        assert pol.decide(RelayObservation(1, relays)) == []

    def test_rehome_refused_once_when_no_target(self):
        pol = RelayPolicy()
        relays = {2: _sample(2, tier=1, parent=9, subs=1, alive=False)}
        acts = pol.decide(RelayObservation(0, relays))
        assert [a.kind for a in acts] == ["refuse"]
        assert pol.decide(RelayObservation(1, relays)) == []

    def test_drain_retire_scale_down_arc(self):
        cfg = RelayAutopilotConfig(
            confirm_beats=1, cooldown_scale_ticks=0, min_relays=1,
        )
        pol = RelayPolicy(cfg)
        two_idle = {
            1: _sample(1, subs=0), 2: _sample(2, subs=0),
        }
        acts = pol.decide(RelayObservation(0, two_idle))
        assert [a.kind for a in acts] == ["relay_drain"]
        assert acts[0].server_id == 2  # emptiest; newest id on ties
        draining = {
            1: _sample(1, subs=0, draining=True), 2: _sample(2, subs=0),
        }
        acts = pol.decide(RelayObservation(1, draining))
        assert [a.kind for a in acts] == ["relay_retire"]


class TestRelayAutopilotArc:
    def _drive(self, net, tree, peers, pub, pilot, subs, ticks, t0=0):
        for t in range(t0, t0 + ticks):
            net.advance(FPS_DT)
            tree.pump(net.now)
            for peer in peers:
                sup_step(net, peer, scripted_input)
            pub.publish(net.now)
            for s in subs:
                s.poll(net.now)
            pilot.step(t)
        return t0 + ticks

    def test_spawn_fanout_drain_arc_replays_identically(self, tmp_path):
        """The whole elastic arc against a REAL in-process tree: load
        pushes fill over the high watermark -> spawn; load leaves ->
        drain -> retire; and the JSONL ledger replays bit-identically
        through a fresh policy (the determinism contract)."""
        net = LoopbackNetwork()
        tree, (mid0,), _, peers, pub = _tree_fixture(
            net, mids=1, max_depth=1,
            server_kwargs=dict(shed_after=0.4),
        )
        tree.fanout_capacity = 2
        pilot = RelayAutopilot(
            tree,
            RelayAutopilotConfig(
                high_watermark=0.8, low_watermark=0.4, confirm_beats=3,
                cooldown_scale_ticks=10, min_relays=1, max_relays=3,
            ),
            metrics=Metrics(),
        )
        codec = StateCodec.for_state(box_game.make_world(2).commit())
        specs = [
            _make_spec(net, ("load", i), [mid0.addr], codec)
            for i in range(2)
        ]
        t = self._drive(net, tree, peers, pub, pilot, specs, 80)
        assert pilot.counts.get("relay_spawn", 0) >= 1  # fill 1.0 >= 0.8
        assert len(tree.live_relays()) >= 3  # root + mid0 + spawned

        # Load leaves: subscribers stop polling, shed after 0.4s, fill
        # drops to zero -> drain the emptiest -> retire it once empty.
        t = self._drive(net, tree, peers, pub, pilot, [], 120, t0=t)
        assert pilot.counts.get("relay_drain", 0) >= 1
        assert pilot.counts.get("relay_retire", 0) >= 1
        assert len([
            a for a in tree.live_relays() if a != ROOT
        ]) < 2 + pilot.counts["relay_spawn"]

        # The arc is a replayable artifact.
        path = str(tmp_path / "relay_ledger.jsonl")
        n = pilot.export_jsonl(path)
        assert n == t
        ok, ticks = verify_relay_ledger(path)
        assert ok and ticks == t
        kinds = {a.kind for a in pilot.actions}
        assert {"relay_spawn", "relay_drain", "relay_retire"} <= kinds

    def test_ledger_divergence_detected(self, tmp_path):
        tree_like = _ScriptedRelayFleet([
            {1: _sample(1, subs=4)} for _ in range(4)
        ])
        pilot = RelayAutopilot(
            tree_like, RelayAutopilotConfig(confirm_beats=2),
        )
        for t in range(4):
            pilot.step(t)
        path = str(tmp_path / "tampered.jsonl")
        pilot.export_jsonl(path)
        lines = open(path).read().splitlines()
        rec = json.loads(lines[2])
        rec["actions"] = []  # erase the recorded spawn
        lines[2] = json.dumps(rec)
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
        ok, _ = verify_relay_ledger(path)
        assert not ok

    def test_cli_routes_relay_ledgers(self, tmp_path):
        from bevy_ggrs_tpu.fleet.autopilot import _ledger_kind, _main

        tree_like = _ScriptedRelayFleet([
            {1: _sample(1, subs=4)} for _ in range(3)
        ])
        pilot = RelayAutopilot(
            tree_like, RelayAutopilotConfig(confirm_beats=2),
        )
        for t in range(3):
            pilot.step(t)
        path = str(tmp_path / "relay.jsonl")
        pilot.export_jsonl(path)
        recs = [json.loads(line) for line in open(path)]
        assert _ledger_kind(recs) == "relay"
        assert _main([path]) == 0


class _ScriptedRelayFleet:
    """Adapter returning scripted samples; executors always succeed."""

    def __init__(self, script):
        self.script = list(script)
        self.i = 0

    def relay_samples(self):
        s = self.script[min(self.i, len(self.script) - 1)]
        self.i += 1
        return dict(s)

    def spawn_relay(self):
        return True

    def drain_relay(self, rid):
        return True

    def retire_relay(self, rid):
        return True

    def rehome(self, rid, dst):
        return True


# ---------------------------------------------------------------------------
# Plan stability (satellite: RelayTreeKill drawn LAST)
# ---------------------------------------------------------------------------


class TestRelayTreePlanStability:
    def test_relay_tree_kill_drawn_last_prefix_byte_stable(self):
        """Adding the relay_tree domain must append exactly one
        RelayTreeKill AFTER every existing draw: a seed's pre-tree plan
        stays byte-identical (the pinned replay-artifact contract)."""
        kw = dict(
            peers=(("peer", 0), ("peer", 1)), kill_restart=True,
            relay=("relay", 0), fleet=(1, 2), fleet_matches=3,
            elastic=True, control=True, sdc=True,
        )
        base = ChaosPlan.generate(40, 9.0, **kw)
        tree = ChaosPlan.generate(
            40, 9.0, relay_tree=(("relay", 1), ("relay", 2)), **kw
        )
        assert tree.directives[: len(base.directives)] == base.directives
        extra = tree.directives[len(base.directives):]
        assert len(extra) == 1 and isinstance(extra[0], RelayTreeKill)
        assert extra[0].relay in (("relay", 1), ("relay", 2))
        assert base.to_json() == ChaosPlan.generate(40, 9.0, **kw).to_json()

    def test_relay_tree_kill_json_roundtrip_and_horizon(self):
        plan = ChaosPlan.generate(
            41, 8.0, peers=(("peer", 0),),
            relay_tree=(("relay", 1),),
        )
        kills = plan.relay_tree_kills()
        assert len(kills) == 1 and kills[0].relay == ("relay", 1)
        back = ChaosPlan.from_json(plan.to_json())
        assert back == plan
        assert back.relay_tree_kills()[0].relay == ("relay", 1)
        assert plan.horizon() >= kills[0].at + kills[0].down_for
        # Hand-built plans roundtrip too (address tuple normalization).
        manual = ChaosPlan(5, (RelayTreeKill(1.0, ("relay", 3), 0.25),))
        assert ChaosPlan.from_json(manual.to_json()) == manual


# ---------------------------------------------------------------------------
# Subprocess relay tier over real UDP
# ---------------------------------------------------------------------------


class TestProcRelayTier:
    def test_subprocess_relay_streams_and_drains(self, tmp_path):
        """One subprocess relay child under an in-process root, real UDP
        both hops: the child's TierLink subscribes up, a UDP spectator
        subscribes down, and the injected stream arrives bitwise. Then
        the drain command flips the child's status beat."""
        import time

        from bevy_ggrs_tpu.transport.udp import UdpSocket

        use_native = os.environ.get("GGRS_NO_NATIVE", "") != "1"
        root_sock = UdpSocket(0, host="127.0.0.1", use_native=use_native)
        root = RelayServer(root_sock, metrics=Metrics())
        state = bytes(range(256)) * 12  # 3 chunks
        for raw in _kf_raws(30, state):
            root.ingest(0, raw)

        tier = ProcRelayTier(
            ("127.0.0.1", root_sock.local_port()),
            base_config={"status_interval_s": 0.1},
            stderr_dir=str(tmp_path),
        )
        try:
            rid = tier.spawn_relay(timeout=60.0)
            assert rid is not None, "child never reported ready"
            child_addr = tier.addr_of(rid)
            spec_sock = UdpSocket(0, host="127.0.0.1", use_native=use_native)
            spec = StreamSpectator(
                spec_sock, relays=[child_addr], session_id=0,
                resub_timeout=2.0,
            )
            deadline = time.monotonic() + 30.0
            while spec.state_bytes is None and time.monotonic() < deadline:
                root.pump()
                spec.poll()
                time.sleep(0.01)
            assert spec.state_bytes == state  # bitwise through 2 UDP hops
            assert spec.current_frame == 30

            samples = tier.relay_samples()
            assert rid in samples and samples[rid].alive
            assert tier.drain_relay(rid)
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                tier.poll()
                if tier.relay_samples()[rid].draining:
                    break
                time.sleep(0.05)
            assert tier.relay_samples()[rid].draining
            spec_sock.close()
        finally:
            tier.close()
            root.close()
        assert [e["event"] for e in tier.events][:2] == ["spawn", "drain"]
