"""P2P host crash recovery: checkpoint, die, restore, re-sync, agree.

Peer A checkpoints every few frames (runner + session via persistence).
Mid-session A "crashes" (socket closed, all objects dropped), restarts
from the newest checkpoint with fresh endpoints, re-runs the sync
handshake against the still-live peer B (endpoints answer SyncRequest
while RUNNING), and the pair converges: B sees interrupt→resume, both
advance, and every exchanged checksum boundary agrees — no desync.
"""

import numpy as np

from bevy_ggrs_tpu.models import box_game
from bevy_ggrs_tpu.runner import RollbackRunner
from bevy_ggrs_tpu.session import (
    EventKind,
    PlayerType,
    PredictionThreshold,
    SessionBuilder,
    SessionState,
)
from bevy_ggrs_tpu.transport.loopback import LoopbackNetwork
from bevy_ggrs_tpu.utils.persistence import restore_runner, save_runner

from tests.test_p2p import FPS_DT, common_confirmed_checksums, scripted_input

MAXPRED = 8


def build_peer(net, me, clock):
    builder = (
        SessionBuilder(box_game.INPUT_SPEC)
        .with_num_players(2)
        .with_max_prediction_window(MAXPRED)
    )
    for h in range(2):
        if h == me:
            builder.add_player(PlayerType.local(), h)
        else:
            builder.add_player(PlayerType.remote(("peer", h)), h)
    sock = net.socket(("peer", me))
    session = builder.start_p2p_session(sock, clock=clock)
    runner = RollbackRunner(
        box_game.make_schedule(), box_game.make_world(2).commit(),
        max_prediction=MAXPRED, num_players=2, input_spec=box_game.INPUT_SPEC,
    )
    return session, runner, sock


def tick(net, session, runner):
    session.poll_remote_clients()
    events = session.events()
    if session.current_state() != SessionState.RUNNING:
        return events
    for h in session.local_player_handles():
        session.add_local_input(h, scripted_input(h, session.current_frame))
    try:
        requests = session.advance_frame()
    except PredictionThreshold:
        return events
    runner.handle_requests(requests, session)
    return events


def test_host_crash_restore_resync(tmp_path):
    net = LoopbackNetwork(latency=1.5 * FPS_DT, seed=21)
    clock = lambda: net.now
    sess_a, run_a, sock_a = build_peer(net, 0, clock)
    sess_b, run_b, sock_b = build_peer(net, 1, clock)
    ckpt = str(tmp_path / "host.npz")

    events_b = []
    for i in range(60):
        net.advance(FPS_DT)
        tick(net, sess_a, run_a)
        events_b += tick(net, sess_b, run_b)
        if i % 5 == 0 and sess_a.current_state() == SessionState.RUNNING:
            save_runner(ckpt, run_a, session=sess_a)
    frame_at_crash = run_a.frame
    assert frame_at_crash > 30

    # --- crash A: socket closes, objects die --------------------------
    sock_a.close()
    del sess_a, run_a

    # B keeps running alone for a while (will stall at the prediction
    # threshold and mark A interrupted; notify starts after 0.5s = 30
    # virtual frames, so run well past it).
    for _ in range(50):
        net.advance(FPS_DT)
        events_b += tick(net, sess_b, run_b)
    assert any(e.kind == EventKind.NETWORK_INTERRUPTED for e in events_b)

    # --- restart A from the newest checkpoint -------------------------
    sess_a2, run_a2, _ = build_peer(net, 0, clock)
    meta = restore_runner(ckpt, run_a2, session=sess_a2)
    assert run_a2.frame == sess_a2.current_frame == meta["frame"]
    assert run_a2.frame <= frame_at_crash

    events_a2 = []
    for _ in range(200):
        net.advance(FPS_DT)
        events_a2 += tick(net, sess_a2, run_a2)
        events_b += tick(net, sess_b, run_b)

    # Re-synced and progressing on both sides.
    assert sess_a2.current_state() == SessionState.RUNNING
    assert any(e.kind == EventKind.SYNCHRONIZED for e in events_a2)
    assert any(e.kind == EventKind.NETWORK_RESUMED for e in events_b)
    assert run_a2.frame > frame_at_crash
    assert run_b.frame > frame_at_crash
    # All post-resume exchanged checksums agree; desync never fired.
    frames, pairs = common_confirmed_checksums([(sess_a2, run_a2),
                                                (sess_b, run_b)])
    assert frames, "no common checksum boundaries after resume"
    assert all(a == b for a, b in pairs)
    assert not any(e.kind == EventKind.DESYNC_DETECTED
                   for e in events_a2 + events_b)


def test_resume_with_dead_player_does_not_block_sync(tmp_path):
    """A player who disconnected BEFORE the checkpoint must not park the
    restored session in SYNCHRONIZING (its fresh endpoint is
    force-disconnected at restore), and the frozen repeat-last prediction
    for the dead player survives the round trip."""
    from tests.test_p2p_multi import make_group, step_peer

    net = LoopbackNetwork(latency=1 * FPS_DT, seed=4)
    peers = make_group(net, 3, disconnect_timeout=0.3)
    ckpt = str(tmp_path / "abc.npz")

    # Everyone alive for a while.
    for _ in range(30):
        net.advance(FPS_DT)
        for s, r in peers:
            step_peer(s, r, scripted_input)
    # C (handle 2) dies; A and B continue past the disconnect timeout.
    for _ in range(40):
        net.advance(FPS_DT)
        for s, r in peers[:2]:
            step_peer(s, r, scripted_input)
    sa, ra = peers[0]
    assert 2 in sa._disconnected
    frozen = np.asarray(sa._queues[2].last_input).copy()
    save_runner(ckpt, ra, session=sa)
    crash_frame = ra.frame

    # A crashes and restarts; only B (and dead C's silence) remain.
    sa.socket.close()
    del sa, ra
    peers[0] = (None, None)
    net.advance(10 * FPS_DT)

    sock = net.socket(("peer", 0))
    builder = (
        SessionBuilder(box_game.INPUT_SPEC)
        .with_num_players(3)
        .with_max_prediction_window(8)
        .with_disconnect_timeout(0.3)
    )
    builder.add_player(PlayerType.local(), 0)
    builder.add_player(PlayerType.remote(("peer", 1)), 1)
    builder.add_player(PlayerType.remote(("peer", 2)), 2)
    sess_a2 = builder.start_p2p_session(sock, clock=lambda: net.now)
    run_a2 = RollbackRunner(
        box_game.make_schedule(), box_game.make_world(3).commit(),
        max_prediction=8, num_players=3, input_spec=box_game.INPUT_SPEC,
    )
    restore_runner(ckpt, run_a2, session=sess_a2)
    assert 2 in sess_a2._disconnected
    np.testing.assert_array_equal(
        np.asarray(sess_a2._queues[2].last_input), frozen
    )

    sb, rb = peers[1]
    for _ in range(150):
        net.advance(FPS_DT)
        step_peer(sess_a2, run_a2, scripted_input)
        step_peer(sb, rb, scripted_input)
    # Re-synced with B despite C's endpoint never answering.
    assert sess_a2.current_state() == SessionState.RUNNING
    assert run_a2.frame > crash_frame
    frames, pairs = common_confirmed_checksums([(sess_a2, run_a2), (sb, rb)])
    assert frames and all(a == b for a, b in pairs)


def test_spectator_crash_restore(tmp_path):
    """A crashed spectator restores from its newest checkpoint, re-syncs
    with the host, and continues consuming the confirmed stream (the
    host's unacked redundant resend bridges the crash gap because nothing
    past the checkpoint was ever acked)."""
    net = LoopbackNetwork(latency=1 * FPS_DT, seed=8)
    clock = lambda: net.now

    def host_peer(me):
        builder = (
            SessionBuilder(box_game.INPUT_SPEC)
            .with_num_players(2)
            .with_max_prediction_window(MAXPRED)
        )
        for h in range(2):
            builder.add_player(
                PlayerType.local() if h == me else
                PlayerType.remote(("peer", h)), h)
        if me == 0:
            builder.add_player(PlayerType.spectator(("spec", 0)), 2)
        sock = net.socket(("peer", me))
        session = builder.start_p2p_session(sock, clock=clock)
        runner = RollbackRunner(
            box_game.make_schedule(), box_game.make_world(2).commit(),
            max_prediction=MAXPRED, num_players=2,
            input_spec=box_game.INPUT_SPEC)
        return session, runner

    def make_spec():
        sock = net.socket(("spec", 0))
        session = (
            SessionBuilder(box_game.INPUT_SPEC)
            .with_num_players(2)
            .start_spectator_session(("peer", 0), sock, clock=clock)
        )
        runner = RollbackRunner(
            box_game.make_schedule(), box_game.make_world(2).commit(),
            max_prediction=MAXPRED, num_players=2,
            input_spec=box_game.INPUT_SPEC)
        return session, runner, sock

    sess_a, run_a = host_peer(0)
    sess_b, run_b = host_peer(1)
    spec, spec_run, spec_sock = make_spec()
    ckpt = str(tmp_path / "spec.npz")

    def tick_spec():
        spec.poll_remote_clients()
        if spec.current_state() != SessionState.RUNNING:
            return
        try:
            reqs = spec.advance_frame()
        except PredictionThreshold:
            return
        spec_run.handle_requests(reqs, None)

    for _ in range(60):
        net.advance(FPS_DT)
        tick(net, sess_a, run_a)
        tick(net, sess_b, run_b)
        tick_spec()
    assert spec_run.frame > 20
    save_runner(ckpt, spec_run, session=spec)
    crash_frame = spec_run.frame

    spec_sock.close()
    del spec, spec_run
    for _ in range(30):
        net.advance(FPS_DT)
        tick(net, sess_a, run_a)
        tick(net, sess_b, run_b)

    spec2, spec_run2, _ = make_spec()
    restore_runner(ckpt, spec_run2, session=spec2)
    spec, spec_run = spec2, spec_run2
    for _ in range(200):
        net.advance(FPS_DT)
        tick(net, sess_a, run_a)
        tick(net, sess_b, run_b)
        tick_spec()
    assert spec_run.frame > crash_frame + 20
    assert spec.frames_behind_host() < 60
    # The restored spectator's world must equal straight-line simulation of
    # the (fully confirmed, deterministic) input script — a wrong-handle or
    # wrong-frame restore would diverge here.
    from bevy_ggrs_tpu.schedule import make_inputs
    from bevy_ggrs_tpu.state import combine64, checksum

    sched = box_game.make_schedule()
    oracle = box_game.make_world(2).commit()
    for f in range(spec_run.frame):
        bits = np.asarray([scripted_input(h, f) for h in range(2)], np.uint8)
        oracle = sched(oracle, make_inputs(bits))
    assert combine64(checksum(spec_run.state)) == combine64(checksum(oracle))


def test_spectator_stale_checkpoint_fails_loudly(tmp_path):
    """Restoring a checkpoint OLDER than the spectator's last ack leaves an
    unbridgeable gap (the host trimmed those frames on ack); the session
    must raise NotSynchronized with a rejoin message instead of stalling
    silently forever."""
    import pytest

    from bevy_ggrs_tpu.session import NotSynchronized

    net = LoopbackNetwork(latency=1 * FPS_DT, seed=9)
    clock = lambda: net.now

    def host_peer(me):
        builder = (
            SessionBuilder(box_game.INPUT_SPEC)
            .with_num_players(2)
            .with_max_prediction_window(MAXPRED)
        )
        for h in range(2):
            builder.add_player(
                PlayerType.local() if h == me else
                PlayerType.remote(("peer", h)), h)
        if me == 0:
            builder.add_player(PlayerType.spectator(("spec", 0)), 2)
        session = builder.start_p2p_session(net.socket(("peer", me)),
                                            clock=clock)
        runner = RollbackRunner(
            box_game.make_schedule(), box_game.make_world(2).commit(),
            max_prediction=MAXPRED, num_players=2,
            input_spec=box_game.INPUT_SPEC)
        return session, runner

    def make_spec():
        sock = net.socket(("spec", 0))
        session = (
            SessionBuilder(box_game.INPUT_SPEC)
            .with_num_players(2)
            .start_spectator_session(("peer", 0), sock, clock=clock)
        )
        runner = RollbackRunner(
            box_game.make_schedule(), box_game.make_world(2).commit(),
            max_prediction=MAXPRED, num_players=2,
            input_spec=box_game.INPUT_SPEC)
        return session, runner, sock

    sess_a, run_a = host_peer(0)
    sess_b, run_b = host_peer(1)
    spec, spec_run, spec_sock = make_spec()
    ckpt = str(tmp_path / "stale.npz")
    saved = [False]

    def tick_spec():
        spec.poll_remote_clients()
        if spec.current_state() != SessionState.RUNNING:
            return
        try:
            reqs = spec.advance_frame()
        except PredictionThreshold:
            return
        spec_run.handle_requests(reqs, None)

    for i in range(120):
        net.advance(FPS_DT)
        tick(net, sess_a, run_a)
        tick(net, sess_b, run_b)
        tick_spec()
        # STALE checkpoint: taken early, then the spectator keeps acking
        # another ~80 frames before crashing.
        if not saved[0] and spec_run.frame > 15:
            save_runner(ckpt, spec_run, session=spec)
            saved[0] = True
    assert saved[0] and spec_run.frame > 60

    spec_sock.close()
    del spec, spec_run
    spec, spec_run, _ = make_spec()
    restore_runner(ckpt, spec_run, session=spec)

    with pytest.raises(NotSynchronized, match="unbridgeable gap"):
        for _ in range(400):
            net.advance(FPS_DT)
            tick(net, sess_a, run_a)
            tick(net, sess_b, run_b)
            tick_spec()
        raise AssertionError("stale-checkpoint stall was never detected")


def test_speculative_runner_survives_restore(tmp_path):
    """Crash recovery with speculation enabled: the restored runner's
    speculation state (input log, pending rollout) is empty, so it must
    fall back to serial recoveries gracefully, rebuild its log as frames
    advance, and keep both live peers in bitwise agreement after the
    resume."""
    from bevy_ggrs_tpu.spec_runner import SpeculativeRollbackRunner

    def build_spec_runner():
        return SpeculativeRollbackRunner(
            box_game.make_schedule(), box_game.make_world(2).commit(),
            max_prediction=MAXPRED, num_players=2,
            input_spec=box_game.INPUT_SPEC, num_branches=16, spec_frames=8,
        )

    net = LoopbackNetwork(latency=2 * FPS_DT, seed=31)
    clock = lambda: net.now
    sess_a, _discard, _ = build_peer(net, 0, clock)
    run_a = build_spec_runner()
    sess_b, run_b, _ = build_peer(net, 1, clock)
    ckpt = str(tmp_path / "specrun.npz")

    def drive(n):
        for _ in range(n):
            net.advance(FPS_DT)
            for s, r in ((sess_a, run_a), (sess_b, run_b)):
                tick(net, s, r)
                if (hasattr(r, "speculate")
                        and s.current_state() == SessionState.RUNNING):
                    r.speculate(s.confirmed_frame(), s)

    drive(60)
    assert run_a.frame > 30, "handshake too slow: checkpoint would be empty"
    save_runner(ckpt, run_a, session=sess_a)
    sess_a.socket.close()

    builder = (
        SessionBuilder(box_game.INPUT_SPEC)
        .with_num_players(2)
        .with_max_prediction_window(MAXPRED)
    )
    builder.add_player(PlayerType.local(), 0)
    builder.add_player(PlayerType.remote(("peer", 1)), 1)
    sess_a = builder.start_p2p_session(net.socket(("peer", 0)), clock=clock)
    run_a = build_spec_runner()
    restore_runner(ckpt, run_a, session=sess_a)
    drive(150)

    assert run_b.frame > 100  # joint progress after the crash
    frames, pairs = common_confirmed_checksums([(sess_a, run_a),
                                                (sess_b, run_b)])
    assert frames and all(a == b for a, b in pairs)
