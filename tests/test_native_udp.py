"""Native (C++) UDP poller: build, batch drain, transport integration."""

import numpy as np
import pytest

pytest.importorskip("bevy_ggrs_tpu.native.udp", reason="native toolchain unavailable")

from bevy_ggrs_tpu.native.udp import NativeUdpSocket
from bevy_ggrs_tpu.transport.udp import UdpSocket


def free_pair(base=17510):
    return base, base + 1


class TestNativeUdp:
    def test_roundtrip_order_and_addr(self):
        pa, pb = free_pair(17520)
        a, b = NativeUdpSocket(port=pa), NativeUdpSocket(port=pb)
        try:
            for i in range(10):
                a.send_to(bytes([i]) * (i + 1), ("127.0.0.1", pb))
            import time

            time.sleep(0.05)
            got = b.receive_all()
            assert [m for _, m in got] == [bytes([i]) * (i + 1) for i in range(10)]
            assert all(addr == ("127.0.0.1", pa) for addr, _ in got)
        finally:
            a.close()
            b.close()

    def test_empty_drain(self):
        s = NativeUdpSocket(port=17530)
        try:
            assert s.receive_all() == []
        finally:
            s.close()

    def test_large_batch_single_poll(self):
        """More datagrams than one recvmmsg batch still fully drain."""
        pa, pb = free_pair(17540)
        a, b = NativeUdpSocket(port=pa), NativeUdpSocket(port=pb)
        try:
            n = 150  # > kMaxBatch=64
            for i in range(n):
                a.send_to(i.to_bytes(2, "little"), ("127.0.0.1", pb))
            import time

            time.sleep(0.1)
            got = b.receive_all()
            assert len(got) == n
            assert [int.from_bytes(m, "little") for _, m in got] == list(range(n))
        finally:
            a.close()
            b.close()

    def test_transport_uses_native(self):
        s = UdpSocket(17550)
        try:
            assert s._native is not None, "UdpSocket should pick the native poller"
            s.send_to(b"ping", ("127.0.0.1", 17550))
            import time

            time.sleep(0.05)
            got = s.receive_all()
            assert got and got[0][1] == b"ping"
        finally:
            s.close()
